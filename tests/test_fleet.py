"""Scenario fleets: the vmapped engine's headline pins (ISSUE 15).

A `Fleet` runs L scenario lanes of one compiled window loop as ONE
jitted vmapped program. The contract this file pins, lane by lane:

- bit-identity: lane k's final state tree AND summary equal a solo run
  built the native way (Engine with that lane's seed / compiled fault
  schedule / scaled network) — for seed sweeps, mixed fault schedules,
  and latency scalings in the SAME fleet;
- no bleed: a lane with no faults inside a faulted fleet matches the
  fault-free solo run exactly (the padded schedules are values-neutral);
- zero cost: building a fleet leaves the unbatched engine's lowered
  program byte-identical (assert_zero_cost), and the fleet program's op
  histogram is lane-count-independent (L=1 vs L=4);
- donation: the production fleet jit aliases every donated leaf of the
  stacked [L, ...] carry (no per-window copy of the fleet state);
- census: a fleet heartbeat segment performs exactly ONE jax.device_get;
- CLI: `--window auto` + `--fleet` is rejected with an actionable error
  before any lane compiles.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shadow_tpu.core.engine import Engine
from shadow_tpu.core.engine import state_summary
from shadow_tpu.core.timebase import SECOND
from shadow_tpu.faults import parse_fault_dsl
from shadow_tpu.faults.schedule import compile_faults
from shadow_tpu.models import phold
from shadow_tpu.runtime.fleet import (
    FleetPlan,
    build_fleet_from_engine,
    check_lane_knobs,
    scaled_network,
)

N = 8  # hosts
STOP = 3 * SECOND
NAMES = [f"host{i}" for i in range(N)]

CRASH = parse_fault_dsl("crash hosts=host3 start=1 end=2")
LOSSY = parse_fault_dsl("loss src=host1 dst=host5 loss=0.5 start=1 end=2")


def _phold(seed):
    return phold.build(N, seed=seed, capacity=64, msgs_per_host=2)


def _solo_final(seed, faults=(), scale=None):
    """The native solo build for one lane's scenario: faults compiled
    into the Engine constructor (NOT bind_lane — the comparison must
    cross implementations), latency scaling via scaled_network."""
    eng, init = _phold(seed)
    st0 = init()
    if faults or scale is not None:
        net = (scaled_network(eng.network, scale)
               if scale is not None else eng.network)
        comp = None
        reset = None
        if faults:
            comp = compile_faults(tuple(faults), NAMES, N, seed)
            if comp.has_crash or comp.has_bw:
                reset = st0.hosts
        eng = Engine(eng.cfg, eng.handlers, net,
                     batch_handler=eng.batch_handler,
                     faults=comp, fault_reset=reset)
    return jax.device_get(jax.jit(eng.run)(st0, jnp.int64(STOP)))


def _lane(state, k):
    return jax.tree.map(lambda x: np.asarray(x)[k], state)


def _assert_tree_equal(a, b, label):
    la = jax.tree.leaves(a)
    lb = jax.tree.leaves(b)
    assert len(la) == len(lb), label
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                      err_msg=label)


# ----------------------------------------------------------- bit-identity


@pytest.fixture(scope="module")
def mixed_fleet():
    """4 lanes exercising every per-lane knob class at once: a plain
    lane, a crash lane, a loss lane, and a latency-scaled lane."""
    eng, init = _phold(0)
    fleet = build_fleet_from_engine(
        eng, init(), 4,
        seeds=(11, 12, 13, 14),
        faults=(None, (CRASH,), (LOSSY,), None),
        latency_scale=(1.0, 1.0, 1.0, 1.7),
    )
    final = jax.device_get(fleet.run(STOP))
    return fleet, final


@pytest.mark.slow  # three fresh solo compiles; the tier-1 smoke lane keeps the
# mixed-fault identity pin + every guard test under the 870s budget
def test_seed_sweep_lanes_bit_identical_to_solo():
    eng, init = _phold(0)
    fleet = build_fleet_from_engine(eng, init(), 3, seeds=(5, 6, 7))
    final = jax.device_get(fleet.run(STOP))
    sums = fleet.lane_summaries(final)
    for k, seed in enumerate((5, 6, 7)):
        solo = _solo_final(seed)
        _assert_tree_equal(_lane(final, k), solo, f"lane {k} state")
        assert sums[k] == state_summary(solo), f"lane {k} summary"
    # the sweep actually varied: different seeds, different trajectories
    assert len({s["executed"] for s in sums}) > 1


@pytest.mark.slow  # fleet compile + four solo compiles via the module fixture;
# the full lane (`-m slow`) keeps this acceptance pin while tier-1 holds 870s
def test_mixed_fault_fleet_lanes_bit_identical_to_solo(mixed_fleet):
    fleet, final = mixed_fleet
    cases = [(11, (), None), (12, (CRASH,), None),
             (13, (LOSSY,), None), (14, (), 1.7)]
    sums = fleet.lane_summaries(final)
    for k, (seed, faults, scale) in enumerate(cases):
        solo = _solo_final(seed, faults=faults, scale=scale)
        _assert_tree_equal(_lane(final, k), solo, f"lane {k} state")
        assert sums[k] == state_summary(solo), f"lane {k} summary"


@pytest.mark.slow  # rides the same compile-heavy fixture + two solo runs
def test_fault_schedules_do_not_bleed_across_lanes(mixed_fleet):
    # lane 0 rides a fleet whose siblings compiled crash+loss overlays;
    # its state must equal the NO-fault solo run — the padded schedule
    # rows are values-neutral, not merely approximately so
    fleet, final = mixed_fleet
    solo = _solo_final(11)
    _assert_tree_equal(_lane(final, 0), solo, "no-fault lane")
    # and the crash lane visibly diverges from its fault-free twin
    crashed = fleet.lane_summaries(final)[1]
    assert crashed != state_summary(_solo_final(12))


# -------------------------------------------------------------- zero cost


@pytest.mark.slow  # four full lowerings; the tier-1 smoke lane keeps the
# mixed-fault identity pin + every guard test under the 870s budget
def test_fleet_off_is_zero_cost_and_histogram_lane_count_independent():
    from shadow_tpu.analysis.hlo_audit import (
        assert_zero_cost,
        lower_text,
        ops_histogram,
    )

    eng_b, init_b = _phold(3)
    st_b = init_b()
    eng_o, init_o = _phold(3)
    st_o = init_o()
    # building a fleet must leave the base engine untouched: the solo
    # lowering stays byte-identical (the off build feeds a Fleet first)
    fleet1 = build_fleet_from_engine(eng_o, st_o, 1, seeds=(3,))
    fleet2 = build_fleet_from_engine(eng_o, st_o, 2, seeds=(3, 4))
    fleet4 = build_fleet_from_engine(eng_o, st_o, 4, seeds=(3, 4, 5, 6))
    stop = jnp.int64(STOP)
    texts = assert_zero_cost(
        (eng_b, st_b), (eng_o, st_o), (fleet1.run_fn(), fleet1.state0),
        stop,
    )
    # lane-count independence: the L=2 and L=4 programs differ only in
    # the batch dimension's EXTENT — same ops, same counts. (L=1 elides
    # a few size-1 broadcasts, so it is compared on the heavy ops.)
    h2 = ops_histogram(lower_text(fleet2.run_fn(), fleet2.state0, stop))
    h4 = ops_histogram(lower_text(fleet4.run_fn(), fleet4.state0, stop))
    assert h2 == h4
    # and batching adds no scatter and no extra sorts/loops over the
    # solo program (vmap rewrites two dynamic slices into batched
    # gathers — bounded structural overhead, not per-lane bookkeeping)
    h1 = ops_histogram(lower_text(fleet1.run_fn(), fleet1.state0, stop))
    h_solo = ops_histogram(texts["base"])
    for op in ("scatter", "sort", "while"):
        assert h1.get(op, 0) == h2.get(op, 0) == h_solo.get(op, 0), op
    assert h2.get("scatter", 0) == 0
    assert h2.get("gather", 0) - h_solo.get("gather", 0) <= 2


# --------------------------------------------------------------- donation


@pytest.mark.slow  # compiles the production fleet jit; the tier-1 smoke lane keeps the
# mixed-fault identity pin + every guard test under the 870s budget
def test_fleet_jit_donates_the_stacked_carry():
    from shadow_tpu.analysis.donation import audit_jit

    eng, init = _phold(3)
    fleet = build_fleet_from_engine(eng, init(), 4, seeds=(0, 1, 2, 3))
    rep = audit_jit(fleet._jit_run,
                    (fleet.state0, fleet.binds, jnp.int64(STOP)),
                    "fleet_run")
    assert rep["ok"], rep["violations"]
    assert rep["donated_leaves"] == rep["aliased_leaves"] > 0
    assert rep["transfers"] == {}


# ----------------------------------------------------------- harvest path


@pytest.mark.slow  # fleet + harvest compile; the tier-1 smoke lane keeps the
# mixed-fault identity pin + every guard test under the 870s budget
def test_fleet_heartbeat_segment_fetches_exactly_once(monkeypatch):
    from shadow_tpu.runtime.harvest import HeartbeatHarvest

    eng, init = _phold(0)
    fleet = build_fleet_from_engine(eng, init(), 2, seeds=(1, 2))
    harvest = HeartbeatHarvest(fleet)
    st = fleet.dispatch(STOP, None)
    st, bundle = harvest.extract(st, full=True)
    calls = []
    real = jax.device_get
    monkeypatch.setattr(jax, "device_get",
                        lambda x: calls.append(1) or real(x))
    fetched = harvest.fetch(bundle)
    assert len(calls) == 1
    rows = harvest.lane_summaries_from(fetched)
    agg = harvest.summary_from(fetched)
    assert len(rows) == 2
    assert agg["executed"] == sum(r["executed"] for r in rows)
    assert agg["now_ns"] == min(r["now_ns"] for r in rows)
    # the per-lane rows match L solo runs (same seeds, no faults)
    for k, seed in enumerate((1, 2)):
        assert rows[k] == state_summary(_solo_final(seed))


def test_fleet_harvest_rejects_per_scenario_consumers():
    from shadow_tpu.runtime.harvest import HeartbeatHarvest

    eng, init = _phold(0)
    fleet = build_fleet_from_engine(eng, init(), 2, seeds=(1, 2))
    h = HeartbeatHarvest(fleet, tracker=object())
    with pytest.raises(ValueError, match="per-scenario"):
        h._build(True)


# ------------------------------------------------------------- validation


def test_static_knobs_rejected_with_reason():
    with pytest.raises(ValueError, match="static compile-time knob"):
        check_lane_knobs({"capacity": (32, 64)})
    with pytest.raises(ValueError, match="unknown fleet override"):
        check_lane_knobs({"sseeds": (1, 2)})
    with pytest.raises(ValueError, match="entries for 3 lanes"):
        FleetPlan(lanes=3, seeds=(1, 2))


def test_sharded_base_rejected():
    eng, init = phold.build(2, seed=0, capacity=16, axis_name="hosts",
                            n_shards=2)
    with pytest.raises(ValueError, match="single-device engine"):
        build_fleet_from_engine(eng, None, 2)


def test_cli_rejects_window_auto_with_fleet(capsys):
    from shadow_tpu.cli import main

    rc = main(["--test", "--stoptime", "1", "--overflow", "drop",
               "--fleet", "lanes=2 seed=0:2", "--window", "auto"])
    assert rc == 2
    err = capsys.readouterr().err
    assert "--window auto cannot drive a fleet" in err
    assert "--window N" in err  # the actionable remedy


# ---------------------------------------------------------------- tools


FLEET_LOG = """\
[shadow-heartbeat] [fleet-header] time-seconds,lane,seed,now-seconds,\
windows,events,events-delta,queue-drops,fill
[shadow-heartbeat] [fleet] 1,0,11,1,10,100,100,0,0.1000
[shadow-heartbeat] [fleet] 1,1,12,1,10,90,90,0,0.0900
[shadow-heartbeat] [fleet] 2,0,11,2,20,220,120,0,0.1100
[shadow-heartbeat] [fleet] 2,1,12,2,20,200,110,0,0.0800
"""


def test_parse_shadow_learns_fleet_rows():
    from shadow_tpu.tools.parse_shadow import parse_lines

    stats = parse_lines(FLEET_LOG.splitlines())
    assert set(stats["fleet"]) == {"0", "1"}
    lane0 = stats["fleet"]["0"]
    assert lane0["ticks"] == [1, 2]
    assert lane0["seed"] == [11, 11]
    assert lane0["events"] == [100, 220]
    assert lane0["events_delta"] == [100, 120]
    assert lane0["fill"] == [0.1, 0.11]


def test_diff_runs_fleet_logs_diff_lane_by_lane(tmp_path):
    from shadow_tpu.tools import diff_runs

    a = tmp_path / "a.log"
    a.write_text(FLEET_LOG)
    b = tmp_path / "b.log"
    b.write_text(FLEET_LOG.replace("20,200,110,0", "20,201,111,0"))
    assert diff_runs.main([str(a), str(a)]) == 0
    entries = diff_runs.diff_files(str(a), str(b), rtol=0.0)
    keys = {e["key"] for e in entries}
    # only lane 1's sim keys drift; lane 0 stays clean
    assert keys == {"fleet:1.events", "fleet:1.events-delta"}


def test_cli_rejects_per_scenario_flags_and_bad_specs(capsys):
    from shadow_tpu.cli import main

    rc = main(["--test", "--stoptime", "1", "--overflow", "drop",
               "--fleet", "lanes=2 seed=0:2", "--metrics"])
    assert rc == 2
    assert "per-scenario" in capsys.readouterr().err
    rc = main(["--test", "--stoptime", "1", "--overflow", "drop",
               "--fleet", "lanes=2 seed=0:5"])
    assert rc == 2
    assert "2 lanes" in capsys.readouterr().err


def test_phold_build_fleet_convenience_defaults():
    # the model-level sweep entry point bench.py and perf_smoke use:
    # seeds default to base seed .. base seed + L - 1
    fleet = phold.build_fleet(N, 3, seed=7, capacity=64, msgs_per_host=2)
    assert fleet.lanes == 3
    assert tuple(int(s) for s in fleet.plan.seeds) == (7, 8, 9)
    fleet = phold.build_fleet(N, 2, seeds=(11, 4), capacity=64,
                              msgs_per_host=2)
    assert tuple(int(s) for s in fleet.plan.seeds) == (11, 4)

"""Static-analysis layer (shadow_tpu/analysis/): lint rules, baseline
workflow, and the HLO contract auditor.

Each lint rule gets a fixture snippet that must trip it and a nearby
idiom that must NOT (the exemptions are as load-bearing as the rules:
bool-compare counts, counter-based stream RNG, ctypes protocol
attributes). The auditor is exercised against the real phold engine —
clean by contract — and against an injected forbidden-op variant it
must reject. The five-config audit runs in the slow lane (and in the
measure_all.sh lint stage); docs/10-Static-Analysis.md is the catalog.
"""

import dataclasses
import json
import textwrap

import jax
import jax.numpy as jnp
import pytest

from shadow_tpu.analysis import hlo_audit as H
from shadow_tpu.analysis import lint as L
from shadow_tpu.core.timebase import MILLISECOND
from shadow_tpu.models import phold


def _lint(src: str, path: str = "<fixture>"):
    return L.lint_source(textwrap.dedent(src), path)


def _rules(findings):
    return sorted({f.rule for f in findings})


# ------------------------------------------------------------- lint rules


def test_sl101_host_materialization_in_jit():
    fs = _lint("""
        import jax, numpy as np
        @jax.jit
        def f(x):
            y = float(x)
            z = np.sin(x)
            w = x.item()
            return y + z + w
    """)
    assert _rules(fs) == ["SL101"] and len(fs) == 3


def test_sl101_silent_outside_jit():
    fs = _lint("""
        import numpy as np
        def host_side(arr):
            return float(np.sin(arr).sum())
    """)
    assert fs == []


def test_sl102_tracer_branch_in_jit():
    fs = _lint("""
        import jax
        @jax.jit
        def f(x):
            if x > 0:
                return x
            while x < 3:
                x = x + 1
            return x
    """)
    assert _rules(fs) == ["SL102"] and len(fs) == 2


def test_sl102_static_tests_whitelisted():
    # isinstance / `is None` / self.cfg-rooted flags are static dispatch
    fs = _lint("""
        import jax
        @jax.jit
        def f(self, x, flag=None):
            if x is None:
                return 0
            if isinstance(x, tuple):
                return 1
            if self.cfg.trace:
                return 2
            return x
    """)
    assert fs == []


def test_sl102_marks_while_loop_bodies():
    # jit scope via lax.while_loop reference, not a decorator
    fs = _lint("""
        import jax
        from jax import lax
        def outer(st0):
            def body(st):
                if st > 0:
                    st = st - 1
                return st
            def cond(st):
                return st > 0
            return lax.while_loop(cond, body, st0)
    """)
    assert "SL102" in _rules(fs)


def test_sl103_i32_time_cast():
    fs = _lint("""
        import jax.numpy as jnp
        def g(due_time):
            a = due_time.astype(jnp.int32)
            b = jnp.int32(due_time)
            delay_ns = jnp.zeros(4, dtype=jnp.int32)
            return a, b, delay_ns
    """)
    assert _rules(fs) == ["SL103"] and len(fs) == 3


def test_sl103_bool_compare_counts_exempt():
    # `sum(t != INVALID, dtype=int32)` counts booleans derived from
    # time — count arithmetic, not time truncation (engine idiom)
    fs = _lint("""
        import jax.numpy as jnp
        def g(stage_time, TIME_INVALID):
            n = jnp.sum(stage_time != TIME_INVALID, axis=1,
                        dtype=jnp.int32)
            return n
    """)
    assert fs == []


def test_sl104_prng_key_reuse():
    fs = _lint("""
        from shadow_tpu.core import rng as srng
        def h(key):
            a = srng.uniform(key)
            b = srng.randint(key, 0, 4)
            return a, b
    """)
    assert _rules(fs) == ["SL104"]


def test_sl104_split_and_streams_exempt():
    fs = _lint("""
        from shadow_tpu.core import rng as srng
        def h(key, seed):
            k1, k2 = srng.split(key, 2)
            a = srng.uniform(k1)
            b = srng.randint(k2, 0, 4)
            u = srng.fault_stream_uniform(seed, 1, 8)
            v = srng.fault_stream_uniform(seed, 2, 8)
            return a, b, u, v
    """)
    assert fs == []


def test_sl104_reassignment_resets():
    fs = _lint("""
        from shadow_tpu.core import rng as srng
        def h(key):
            a = srng.uniform(key)
            key = srng.fold_in(key, 1)
            b = srng.uniform(key)
            return a, b
    """)
    assert fs == []


def test_sl105_mutable_defaults():
    fs = _lint("""
        def f(x, acc=[]):
            acc.append(x)
            return acc
        class C:
            registry = {}
    """)
    assert _rules(fs) == ["SL105"] and len(fs) == 2


def test_sl105_ctypes_fields_exempt():
    fs = _lint("""
        import ctypes
        class Req(ctypes.Structure):
            _fields_ = [("pid", ctypes.c_int32)]
    """)
    assert fs == []


def test_sl106_set_iteration():
    fs = _lint("""
        import jax
        @jax.jit
        def f(x):
            out = [x[i] for i in {2, 1, 0}]
            for k in set((1, 2)):
                out.append(k)
            return out
    """)
    assert _rules(fs) == ["SL106"] and len(fs) == 2


def test_sl107_undonated_entry_points():
    # all three resolution paths: named entry point, in-file def with a
    # state parameter, and a state-carrying lambda
    fs = _lint("""
        import jax
        def step_window(state, stop, base, window):
            return state
        def drive(st, stop):
            return st
        j1 = jax.jit(step_window)
        j2 = jax.jit(drive)
        j3 = jax.jit(lambda st, stop: st)
    """)
    assert _rules(fs) == ["SL107"] and len(fs) == 3


def test_sl107_donated_clean():
    fs = _lint("""
        import jax
        def run(state, stop):
            return state
        j1 = jax.jit(run, donate_argnums=0)
        j2 = jax.jit(lambda st, stop: st, donate_argnames="st")
        j3 = jax.jit(lambda x, y: x + y)  # no state carry at all
    """)
    assert fs == []


def test_sl107_no_donate_exemption_needs_reason():
    # the reasoned marker suppresses; a bare `no-donate=` does not
    fs = _lint("""
        import jax
        def run(state, stop):
            return state
        j = jax.jit(run)  # shadowlint: no-donate=pmap fallback stacks outputs
    """)
    assert fs == []
    fs = _lint("""
        import jax
        def run(state, stop):
            return state
        j = jax.jit(run)  # shadowlint: no-donate=
    """)
    assert _rules(fs) == ["SL107"]


def test_sl108_collective_in_named_cond_fun():
    # named cond function resolved by the pass-1 predicate marking —
    # the exact shape of the PR-1 miscompile
    fs = _lint("""
        import jax
        from jax import lax
        def drain(q, stop, ax):
            def cond(carry):
                return lax.psum(carry[1], ax) > 0
            def body(carry):
                return carry
            return lax.while_loop(cond, body, (q, stop))
    """)
    assert _rules(fs) == ["SL108"]


def test_sl108_collective_in_lambda_cond_and_cond_pred():
    fs = _lint("""
        import jax
        from jax import lax
        def f(x, ax):
            y = lax.while_loop(
                lambda c: lax.pmin(c, ax) < 9, lambda c: c + 1, x)
            return jax.lax.cond(
                lax.psum(y, ax) > 0, lambda v: v, lambda v: -v, y)
    """)
    # one finding per collective: the pmin in the while's lambda cond
    # AND the psum in lax.cond's predicate expression
    assert [f.rule for f in fs] == ["SL108", "SL108"]


def test_sl108_wrapper_and_method_cond():
    # the engine's reduction wrappers count, and attribute conds
    # (self._more) resolve through pass-1 marking too
    fs = _lint("""
        import jax
        class Eng:
            def _more(self, carry):
                return self._gany(carry[0])
            def loop(self, st):
                return jax.lax.while_loop(
                    self._more, lambda c: c, (st, 0))
    """)
    assert _rules(fs) == ["SL108"]


def test_sl108_carried_flag_clean():
    # the restructured engine shape: flag computed in the BODY,
    # predicate only reads the carry — no finding
    fs = _lint("""
        import jax
        from jax import lax
        def drain(q, stop, ax):
            def cond(carry):
                return carry[0]
            def body(carry):
                flag, q = carry
                return lax.psum(flag, ax) > 0, q
            return lax.while_loop(
                cond, body, (lax.psum(q, ax) > 0, q))
    """)
    assert fs == []


def test_sl109_blocking_sync_outside_jit():
    fs = _lint("""
        import jax
        def poll(st):
            now = int(jax.device_get(st.now))
            st.queues.drops.block_until_ready()
            return now
    """)
    assert _rules(fs) == ["SL109"] and len(fs) == 2


def test_sl109_in_jit_is_sl101_not_sl109():
    # mutually exclusive by construction: inside jit scope the same
    # calls are SL101's host-materialization finding
    fs = _lint("""
        import jax
        @jax.jit
        def f(x):
            return jax.device_get(x)
    """)
    assert _rules(fs) == ["SL101"]


def test_sl109_watchdog_scoped_sites_allowed():
    src = """
        import jax
        class HeartbeatHarvest:
            def fetch(self, bundle):
                return jax.device_get(bundle)
    """
    assert _lint(src) == []
    # the watchdog layer itself is allowed by path
    plain = """
        import jax
        def reap(st):
            return jax.device_get(st.now)
    """
    assert _lint(plain, "shadow_tpu/runtime/supervisor.py") == []
    assert _rules(_lint(plain, "shadow_tpu/runtime/other.py")) == ["SL109"]


def test_sl109_no_deadline_exemption_needs_reason():
    # the reasoned marker suppresses; a bare `no-deadline=` does not
    ok = _lint("""
        import jax
        def probe(st):
            return jax.device_get(st.now)  # shadowlint: no-deadline=build-time fetch
    """)
    assert ok == []
    bare = _lint("""
        import jax
        def probe(st):
            return jax.device_get(st.now)  # shadowlint: no-deadline=
    """)
    assert _rules(bare) == ["SL109"]


def test_sl110_wallclock_in_jit():
    fs = _lint("""
        import time
        import jax
        @jax.jit
        def f(x):
            t0 = time.time()
            t1 = time.perf_counter()
            t2 = time.monotonic_ns()
            return x + t0 + t1 + t2
    """)
    assert _rules(fs) == ["SL110"] and len(fs) == 3


def test_sl110_silent_outside_jit():
    # host-side wall clock is the supervisor/pressure idiom — never a
    # finding outside jit scope (SL110 is about values freezing into
    # compile-time constants, which only tracing can do)
    fs = _lint("""
        import time
        def heartbeat():
            return time.time(), time.monotonic()
    """)
    assert fs == []


def test_sl110_from_import_and_bare_time():
    # `from time import perf_counter` still trips inside jit; a bare
    # `time(...)` call does NOT (too ambiguous — datetime.time, a local
    # helper named time), only the module-attribute form is matched
    fs = _lint("""
        from time import perf_counter
        import jax
        @jax.jit
        def f(x):
            return x + perf_counter()
    """)
    assert _rules(fs) == ["SL110"]
    fs = _lint("""
        import jax
        def time():
            return 0
        @jax.jit
        def f(x):
            return x + time()
    """)
    assert fs == []


def test_sl110_inline_suppression():
    fs = _lint("""
        import time
        import jax
        @jax.jit
        def f(x):
            t = time.time()  # shadowlint: disable=SL110
            return x + t
    """)
    assert fs == []


def test_sl111_double_donate_same_array():
    fs = _lint("""
        import jax
        def f(step, a):
            step2 = jax.jit(step, donate_argnums=(0, 1))
            return step2(a, a)
    """)
    assert _rules(fs) == ["SL111"] and len(fs) == 1
    assert "donated parameters 0 and 1" in fs[0].message


def test_sl111_reuse_after_donation():
    # reading a reference after it was passed to a donated position —
    # the buffer is deleted by the call; both the named-jit and the
    # direct jax.jit(...)(...) forms are tracked
    fs = _lint("""
        import jax
        def g(step, st, stop):
            jstep = jax.jit(step, donate_argnums=0)
            out = jstep(st, stop)
            return out, st.now
    """)
    assert _rules(fs) == ["SL111"] and len(fs) == 1
    assert "`st` was donated" in fs[0].message
    fs = _lint("""
        import jax
        def k(step, st, stop):
            out = jax.jit(step, donate_argnums=0)(st, stop)
            return st + out
    """)
    assert _rules(fs) == ["SL111"]


def test_sl111_rebind_is_clean():
    # the engine convention — st = step(st, stop) — rebinds the name
    # to the jit's output, so later reads are fresh buffers; the
    # run_with_spill window loop is exactly this shape
    fs = _lint("""
        import jax
        def h(step, st, stop):
            jstep = jax.jit(step, donate_argnums=0)
            while int(st.now) < int(stop):
                st = jstep(st, stop)
            return st.now
    """)
    assert fs == []


def test_sl111_undonated_calls_untracked():
    # a jit without donate_argnums consumes nothing (SL107 owns the
    # should-it-donate question for entry points)
    fs = _lint("""
        import jax
        def f(fn, x):
            j = jax.jit(fn)
            y = j(x)
            return x + y
    """)
    assert fs == []


def test_sl112_computed_gather_in_handler_scope():
    # indexing a global table by another host's id gathers the whole
    # [NC] column per host under vmap — both the `_on_*` method
    # convention and make_handlers closures are handler scope
    fs = _lint("""
        class Model:
            def _on_recv(self, hs, slot, pkt, now, key):
                g = self._g
                reply_sz = g["recvsize"][pkt.src_host]
                return reply_sz

            def _make_handlers(self, stack, kind_base):
                g = self._g
                def h_dial(hs, ev, key):
                    return g["dials"][ev.src]
                return (h_dial,)
    """)
    assert _rules(fs) == ["SL112"] and len(fs) == 2


def test_sl112_own_gid_rows_clean():
    # the own-row convention — first index element is the handler's
    # gid (or a static construction) — is an aligned select, not a
    # gather; trailing in-row indices may be computed
    fs = _lint("""
        import jax.numpy as jnp
        class Model:
            def _on_recv(self, hs, slot, pkt, now, key):
                g, me = self._g, hs.gid
                a = g["count"][me]
                b = g["peers"][me, slot % 4]
                c = g["n_blocks"]
                d = g["pause_ns"][0]
                e = g["sendsize"][jnp.arange(4)]
                return a + b + c + d + e
    """)
    assert fs == []


def test_sl112_silent_outside_handler_scope():
    # build-time host code reshuffles global tables freely
    fs = _lint("""
        def build(self, b):
            g = self._g
            order = g["recvsize"][g["peer_gid"]]
            return order
    """)
    assert fs == []


def test_sl112_inline_suppression():
    fs = _lint("""
        class Model:
            def _on_recv(self, hs, slot, pkt, now, key):
                g = self._g
                return g["recvsize"][pkt.src_host]  # shadowlint: disable=SL112
    """)
    assert fs == []


def test_sl113_blocking_socket_in_dispatch_scopes():
    # each window-loop drive scope name trips: the socket call parks
    # the thread in the kernel while the device loop waits behind it
    fs = _lint("""
        def dispatch(stop_ns, state, sock):
            return sock.recv(1024)
        def run(st, stop, httpd):
            httpd.serve_forever()
            return st
        def step_window(st, stop, conn):
            return conn.getresponse()
    """)
    assert _rules(fs) == ["SL113"] and len(fs) == 3


def test_sl113_blocking_socket_in_jit_scope():
    fs = _lint("""
        import jax
        @jax.jit
        def f(x, sock):
            data, addr = sock.accept()
            return x
    """)
    assert _rules(fs) == ["SL113"]


def test_sl113_silent_on_handler_threads():
    # the sanctioned discipline: blocking socket work on HTTP handler
    # threads / plain helper scopes never flags — and a serve_forever
    # passed as a Thread TARGET (attribute reference, no call) is not a
    # blocking call site at all
    fs = _lint("""
        import threading
        def do_GET(self):
            body = self.rfile.recv(4096)
            return body
        def start(httpd):
            t = threading.Thread(target=httpd.serve_forever, daemon=True)
            t.start()
    """)
    assert fs == []


def test_sl113_inline_suppression():
    fs = _lint("""
        def dispatch(stop, state, sock):
            return sock.recv(64)  # shadowlint: disable=SL113
    """)
    assert fs == []


def test_sl114_worker_write_without_lock():
    # a Thread-target method of a lock-owning class writing bare self
    # state races the submitting thread (the supervisor.py
    # compile_graces bug this rule was built from)
    fs = _lint("""
        import threading
        class Svc:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0
                self.jobs = []
                threading.Thread(target=self._worker_loop).start()
            def _worker_loop(self):
                self.count += 1
                self.jobs.append("x")
    """)
    assert _rules(fs) == ["SL114"]
    assert len(fs) == 2  # the augassign and the container mutation


def test_sl114_lock_scope_and_locked_suffix_exempt():
    # the serving discipline: writes under `with self._lock:` / inside
    # a `with self._cond:` wait loop are clean, and `*_locked` methods
    # document that their caller already holds it
    fs = _lint("""
        import threading
        class Svc:
            def __init__(self):
                self._cond = threading.Condition()
                self.count = 0
                threading.Thread(target=self._worker_loop).start()
            def _worker_loop(self):
                with self._cond:
                    self.count += 1
                    self._drain_locked()
            def _drain_locked(self):
                self.count = 0
    """)
    assert fs == []


def test_sl114_handler_shared_chain():
    # a per-request do_* handler mutating the object every request
    # thread shares (the service/server behind the handler) must hold
    # its lock; bare handler attributes are per-request state and the
    # local dict mutation never flags
    fs = _lint("""
        class Handler:
            def do_POST(self):
                self.close_connection = True
                doc = {}
                doc.update(status="ok")
                self.service.total += 1
                self.service.log.append("x")
            def do_GET(self):
                with self.service._lock:
                    self.service.total += 1
    """)
    assert _rules(fs) == ["SL114"]
    assert len(fs) == 2
    assert all(f.func == "Handler.do_POST" for f in fs)


def test_sl114_silent_outside_thread_entry_and_suppression():
    # plain methods (not do_*, never a Thread target) are unchecked
    # even in lock-owning classes — single-threaded mutation is the
    # default — and the inline marker works where a handler write is
    # deliberate (e.g. the object does its own internal locking)
    fs = _lint("""
        import threading
        class Svc:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0
            def bump(self):
                self.count += 1
        class Handler:
            def do_GET(self):
                self.tracer.spans.append("x")  # shadowlint: disable=SL114
    """)
    assert fs == []


def test_inline_suppression():
    fs = _lint("""
        from shadow_tpu.core import rng as srng
        def h(key):
            a = srng.uniform(key)
            b = srng.randint(key, 0, 4)  # shadowlint: disable=SL104
            return a, b
    """)
    assert fs == []


def test_suppression_is_rule_scoped():
    # disabling SL101 does not silence an SL104 on the same line
    fs = _lint("""
        from shadow_tpu.core import rng as srng
        def h(key):
            a = srng.uniform(key)
            b = srng.randint(key, 0, 4)  # shadowlint: disable=SL101
            return a, b
    """)
    assert _rules(fs) == ["SL104"]


# ------------------------------------------------------ baseline workflow


def test_baseline_roundtrip(tmp_path):
    src = textwrap.dedent("""
        from shadow_tpu.core import rng as srng
        def h(key):
            a = srng.uniform(key)
            b = srng.randint(key, 0, 4)
            return a, b
    """)
    findings = L.lint_source(src, "fixture.py")
    assert findings

    path = str(tmp_path / "baseline.json")
    L.save_baseline(findings, path)
    baseline = L.load_baseline(path)

    # accepted findings don't block...
    new, old, stale = L.split_new(findings, baseline)
    assert new == [] and len(old) == len(findings) and stale == []

    # ...a new finding does...
    worse = src + "    c = srng.uniform(key)\n"
    new2, _, _ = L.split_new(L.lint_source(worse, "fixture.py"), baseline)
    assert len(new2) >= 1

    # ...and keys survive pure line drift (comment above the finding)
    drifted = src.replace("def h(key):", "# a comment\ndef h(key):")
    new3, old3, _ = L.split_new(L.lint_source(drifted, "fixture.py"),
                                baseline)
    assert new3 == [] and len(old3) == len(findings)

    # fixed findings surface as stale keys, not errors
    _, _, stale4 = L.split_new([], baseline)
    assert len(stale4) == len(baseline)


def test_repo_is_lint_clean():
    """The acceptance gate: zero findings outside the checked-in
    baseline across the whole package."""
    new, _, _ = L.split_new(L.lint_package(), L.load_baseline())
    assert new == [], "\n".join(str(f) for f in new)


# ------------------------------------------------------------- hlo audit


def test_audit_text_budgets_and_callbacks():
    contract = H.HloContract("t", {"scatter": 1, "custom_call": 0})
    clean = 'stablehlo.sort ...\nstablehlo.scatter ...\n'
    assert H.audit_text(clean, contract) == []
    over = clean + 'stablehlo.scatter ...\n'
    assert any("scatter" in v for v in H.audit_text(over, contract))
    cb = clean + 'stablehlo.outfeed ...\n'
    assert any("outfeed" in v for v in H.audit_text(cb, contract))


def test_audit_text_custom_call_allowlist():
    contract = H.HloContract("t", {"scatter": 0, "custom_call": 2},
                             custom_call_allow=("Sharding",))
    ok = 'stablehlo.custom_call @x {call_target_name = "Sharding"}\n'
    assert H.audit_text(ok, contract) == []
    bad = 'stablehlo.custom_call @x {call_target_name = "MyOp"}\n'
    assert any("MyOp" in v for v in H.audit_text(bad, contract))
    pycb = ('stablehlo.custom_call @x '
            '{call_target_name = "xla_python_cpu_callback"}\n')
    assert any("host-callback" in v for v in H.audit_text(pycb, contract))


@pytest.fixture(scope="module")
def phold_build():
    eng, init = phold.build(8, seed=3, capacity=32, msgs_per_host=2)
    return eng, init()


def test_phold_engine_meets_contract(phold_build):
    eng, st = phold_build
    text = H.lower_text(eng.run, st, jnp.int64(400 * MILLISECOND))
    assert H.audit_text(text, H.CONTRACTS["phold"]) == []
    assert H.ops_histogram(text)["scatter"] == 0


def test_injected_scatter_is_rejected(phold_build):
    """An engine variant smuggling a scatter into the run must fail the
    phold contract — the auditor sees through the real lowering, not a
    string fixture."""
    eng, st = phold_build

    def bad_run(st, stop):
        out = eng.run(st, stop)
        idx = jnp.array([1, 3])
        return dataclasses.replace(
            out, cpu_free=out.cpu_free.at[idx].add(1))

    text = H.lower_text(bad_run, st, jnp.int64(400 * MILLISECOND))
    violations = H.audit_text(text, H.CONTRACTS["phold"])
    assert violations and all("scatter" in v for v in violations)


def test_assert_zero_cost_catches_residue():
    """The shared helper must fail when the 'off' build is not actually
    identical — checked on toy pytrees so the failure mode is cheap."""
    def mk(extra):
        st = {"a": jnp.zeros(4, jnp.int64)}
        if extra:
            st["b"] = jnp.zeros(2, jnp.int64)
        return (lambda s, stop: jax.tree.map(lambda x: x + stop, s)), st

    base_f, base_st = mk(False)
    on_f, on_st = mk(True)
    # healthy triple passes and returns the three texts
    texts = H.assert_zero_cost((base_f, base_st), (base_f, dict(base_st)),
                               (on_f, on_st), jnp.int64(1),
                               get_subtree=lambda s: s.get("b"))
    assert texts["base"] == texts["off"] != texts["on"]
    # off build with residue fails
    with pytest.raises(AssertionError):
        H.assert_zero_cost((base_f, base_st), (on_f, on_st),
                           (on_f, on_st), jnp.int64(1))


def test_recompile_guard(phold_build):
    eng, st = phold_build
    stop = 100 * MILLISECOND
    H.assert_no_recompile(eng.run,
                          [(st, jnp.int64(stop)), (st, jnp.int64(2 * stop))])
    with pytest.raises(AssertionError):
        # dtype flip across calls = a second program
        H.assert_no_recompile(lambda x: x * 2,
                              [(jnp.int64(3),), (jnp.float32(3.0),)])


@pytest.mark.slow
def test_all_model_configs_meet_contracts():
    """The full five-config audit (also the measure_all.sh lint stage):
    every declared contract holds on today's lowerings."""
    results = H.audit_all()
    assert sorted(results) == sorted(H.CONTRACTS)
    bad = {k: v["violations"] for k, v in results.items() if not v["ok"]}
    assert not bad, json.dumps(bad, indent=1)


# ------------------------------------------------------------------- CLI


def test_cli_exits_nonzero_on_findings(tmp_path):
    from shadow_tpu.tools.lint import main

    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        from shadow_tpu.core import rng as srng
        def h(key):
            a = srng.uniform(key)
            b = srng.randint(key, 0, 4)
            return a, b
    """))
    out = tmp_path / "report.json"
    rc = main([str(bad), "--no-baseline", "--output", str(out)])
    assert rc == 1
    report = json.loads(out.read_text())
    assert report["new"] == 1
    assert report["findings"][0]["rule"] == "SL104"


def test_cli_exits_zero_on_repo(tmp_path):
    from shadow_tpu.tools.lint import main

    out = tmp_path / "report.json"
    rc = main(["--output", str(out)])
    assert rc == 0
    report = json.loads(out.read_text())
    assert report["new"] == 0

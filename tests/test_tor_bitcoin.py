"""Tor onion-circuit and Bitcoin gossip app models (BASELINE configs 3/5).

Tor: clients fetch fixed-size files through client→guard→middle→exit→
server TCP circuits; every hop relays real (simulated) bytes, so relay
byte counters must show the 3-hop amplification. Bitcoin: miners announce
sequential blocks over a random peer graph; INV/GETDATA/BLOCK relay must
propagate every block to every node.
"""

import textwrap

import jax
import jax.numpy as jnp

from shadow_tpu.config import parse_config
from shadow_tpu.sim import build_simulation

TOPO_1POI = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="packetloss" attr.type="double" for="edge" id="d4" />
  <key attr.name="latency" attr.type="double" for="edge" id="d3" />
  <key attr.name="bandwidthup" attr.type="int" for="node" id="d2" />
  <key attr.name="bandwidthdown" attr.type="int" for="node" id="d1" />
  <graph edgedefault="undirected">
    <node id="poi-1">
      <data key="d1">102400</data>
      <data key="d2">102400</data>
    </node>
    <edge source="poi-1" target="poi-1">
      <data key="d3">20.0</data>
      <data key="d4">0.0</data>
    </edge>
  </graph>
</graphml>"""


def tor_config(n_clients=3, filesize="64KiB", count=2):
    hosts = []
    for kind in ("guard", "middle", "exit"):
        for i in range(2):
            hosts.append(
                f'<host id="{kind}{i}">'
                '<process plugin="tor" starttime="1" arguments="relay"/>'
                "</host>"
            )
    hosts.append(
        '<host id="web0">'
        '<process plugin="tor" starttime="1" arguments="server port=80"/>'
        "</host>"
    )
    for i in range(n_clients):
        hosts.append(
            f'<host id="torclient{i}">'
            f'<process plugin="tor" starttime="3" arguments="client '
            f'server=web0:80 filesize={filesize} count={count} pause=1"/>'
            "</host>"
        )
    return (
        '<shadow stoptime="120">'
        f"<topology><![CDATA[{TOPO_1POI}]]></topology>"
        '<plugin id="tor" path="~/.shadow/bin/shadow-plugin-tor"/>'
        + "".join(hosts)
        + "</shadow>"
    )


def test_tor_circuits_fetch_through_three_hops():
    cfg = parse_config(tor_config())
    sim = build_simulation(cfg, seed=11, n_sockets=16)
    st = sim.run()
    app = st.hosts.app

    n_clients, count, filesize = 3, 2, 64 * 1024
    clients = slice(7, 10)  # 6 relays + web0 + 3 clients
    done = app.streams_done[clients]
    assert done.tolist() == [count] * n_clients, (
        done.tolist(), app.conn_rx[clients].tolist()
    )
    # every client pulled count*filesize through its circuit
    assert (app.conn_rx[clients] >= count * filesize).all()
    # relays moved the reply bytes: total relayed >= 2 relay hops' worth
    # of all replies (guard+middle+exit each see the stream once)
    relayed = int(app.relayed_bytes.sum())
    assert relayed >= 3 * n_clients * count * filesize


def test_tor_deterministic():
    cfg = parse_config(tor_config(n_clients=2, count=1))
    s1 = build_simulation(cfg, seed=4, n_sockets=16).run()
    s2 = build_simulation(cfg, seed=4, n_sockets=16).run()
    assert s1.hosts.app.t_last_done.tolist() == s2.hosts.app.t_last_done.tolist()
    assert int(s1.stats.n_executed.sum()) == int(s2.stats.n_executed.sum())


def btc_config(n_nodes=8, blocks=3, blocksize="256KiB", interval=30):
    hosts = [
        '<host id="miner0">'
        f'<process plugin="bitcoin" starttime="1" arguments="node miner '
        f'peers=3 blocksize={blocksize} interval={interval} blocks={blocks}"/>'
        "</host>"
    ]
    for i in range(1, n_nodes):
        hosts.append(
            f'<host id="btc{i}">'
            f'<process plugin="bitcoin" starttime="1" arguments="node '
            f'peers=3 blocksize={blocksize} interval={interval} blocks={blocks}"/>'
            "</host>"
        )
    return (
        f'<shadow stoptime="{interval * (blocks + 3)}">'
        f"<topology><![CDATA[{TOPO_1POI}]]></topology>"
        '<plugin id="bitcoin" path="~/.shadow/bin/shadow-plugin-bitcoin"/>'
        + "".join(hosts)
        + "</shadow>"
    )


def test_queue_overflow_is_loud():
    """An overloaded host must fail the run, not silently lose events
    (VERDICT round 1 weak #4: the reference's queues are unbounded)."""
    import pytest

    cfg = parse_config(btc_config(blocks=3))
    sim = build_simulation(cfg, seed=9, n_sockets=16, capacity=64)
    with pytest.raises(RuntimeError, match="queue overflow"):
        sim.run()
    # opt-out keeps the counted-drops behavior for benchmarks
    sim2 = build_simulation(cfg, seed=9, n_sockets=16, capacity=64)
    sim2.strict_overflow = False
    st = sim2.run()
    assert int(st.queues.drops.sum()) > 0


def test_bitcoin_blocks_reach_every_node():
    blocks = 3
    cfg = parse_config(btc_config(blocks=blocks))
    # a serving node floods its queue while pushing a block to several
    # peers at once; 256 slots overflow (loudly) at this fan-out
    sim = build_simulation(cfg, seed=9, n_sockets=16, capacity=512)
    st = sim.run()
    # device arrays may carry inert shape-bucket padding past the real
    # host count; assertions address the real rows
    n = len(sim.names)
    app = jax.tree.map(lambda a: a[:n], st.hosts.app)

    assert app.best.tolist() == [blocks] * 8, (
        app.best.tolist(), app.curr_dl.tolist()
    )
    # block bodies actually crossed the TCP links
    body_bytes = int(app.dl_rx.sum())
    assert body_bytes >= (8 - 1) * blocks * 256 * 1024
    # propagation: non-miners adopt strictly after the miner
    t_miner = int(app.t_best[0])
    assert all(int(t) > t_miner for t in app.t_best[1:])

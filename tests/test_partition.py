"""Locality-aware host partitioning (the static replacement for the
reference's random host shuffle + work stealing, scheduler.c:440-534).

The measured contract (VERDICT r02 item 6): on the TGen pair config,
locality ordering drops cross-shard packet count by more than 2x vs
naive config order, with identical per-host results (matched by name).
"""

import textwrap

import jax

from shadow_tpu.config import expand_hosts, parse_config
from shadow_tpu.parallel.mesh import make_mesh
from shadow_tpu.parallel.partition import (
    locality_order,
    traffic_edges_from_config,
)
from shadow_tpu.sim import build_simulation

TOPO = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="packetloss" attr.type="double" for="edge" id="d4" />
  <key attr.name="latency" attr.type="double" for="edge" id="d3" />
  <key attr.name="bandwidthup" attr.type="int" for="node" id="d2" />
  <key attr.name="bandwidthdown" attr.type="int" for="node" id="d1" />
  <graph edgedefault="undirected">
    <node id="poi-1">
      <data key="d1">10240</data>
      <data key="d2">10240</data>
    </node>
    <edge source="poi-1" target="poi-1">
      <data key="d3">25.0</data>
      <data key="d4">0.0</data>
    </edge>
  </graph>
</graphml>"""


def pair_config(n_pairs: int) -> str:
    """Interleave servers and clients so NAIVE block order splits every
    pair across shard boundaries at most; client i talks only to server
    i (the dryrun TGen shape)."""
    hosts = []
    for i in range(n_pairs):
        hosts.append(
            f'<host id="server{i}"><process plugin="tgen" starttime="1" '
            f'arguments="server port=8888"/></host>'
        )
    for i in range(n_pairs):
        hosts.append(
            f'<host id="client{i}"><process plugin="tgen" starttime="2" '
            f'arguments="peers=server{i}:8888 sendsize=4KiB '
            f'recvsize=16KiB count=2"/></host>'
        )
    return textwrap.dedent(f"""\
    <shadow stoptime="15">
      <topology><![CDATA[{TOPO}]]></topology>
      <plugin id="tgen" path="tgen"/>
      {''.join(hosts)}
    </shadow>""")


def test_edges_and_order_group_pairs():
    cfg = parse_config(pair_config(8))
    hosts = expand_hosts(cfg)
    edges = traffic_edges_from_config(hosts)
    # every client names its server exactly once -> 8 edges
    assert len(edges) == 8
    perm = locality_order(16, edges, 4)
    # each shard of 4 must hold its pairs together: position blocks of 4
    for s in range(4):
        block = set(perm[4 * s:4 * (s + 1)])
        for g in list(block):
            peer = [b for a, b, _ in edges if a == g] + [
                a for a, b, _ in edges if b == g
            ]
            assert all(p in block for p in peer)


def test_split_cluster_prefers_one_dcn_slice():
    """A cluster that must split (fragmented free space) lands within
    a single slice on a 2-slice mesh — its internal traffic then rides
    ICI, not DCN — while every shard still ends exactly full."""
    # 12 hosts, 4 shards of cap=3, 2 slices of 2 shards. Six pair
    # clusters of 2: after four shards each take one pair (2/3 full),
    # the last two pairs fit NO shard whole and take the split path.
    pairs = [(2 * i, 2 * i + 1, 5) for i in range(6)]
    perm = locality_order(12, pairs, 4, dcn_slices=2)
    assert sorted(perm) == list(range(12))
    half = len(perm) // 2  # slice 0 owns positions 0..5 (dcn-major)
    for a, b, _ in pairs:
        pa, pb = perm.index(a), perm.index(b)
        assert (pa < half) == (pb < half), (a, b, perm)
    # equal shards of exactly cap distinct hosts
    assert all(len(set(perm[i:i + 3])) == 3 for i in range(0, 12, 3))


def test_locality_halves_cross_shard_packets():
    mesh = make_mesh(8)
    # 8 pairs over 8 shards: smallest shape where naive interleaving
    # still straddles shards while locality packs each pair onto one
    cfg_text = pair_config(8)  # 16 hosts, 2 per shard

    crosses, totals = [], {}
    for locality in (False, True):
        sim = build_simulation(
            parse_config(cfg_text), seed=5, mesh=mesh, locality=locality
        )
        st = sim.run()
        cross = int(jax.device_get(st.stats.n_cross_shard.sum()))
        crosses.append(cross)
        # per-host results keyed by NAME (locality permutes gids)
        rx = jax.device_get(st.hosts.net.sockets.rx_bytes.sum(axis=1))
        totals[locality] = {
            name: int(rx[g]) for g, name in enumerate(sim.names)
        }
    naive, local = crosses
    print(f"cross-shard packets: naive={naive} locality={local}")
    # interleaved pairs straddle shards under naive order; locality puts
    # every pair on one shard, so cross-shard traffic collapses
    assert local * 2 < naive, (naive, local)
    # identical simulation results, host-by-host (matched by name)
    assert totals[False] == totals[True]

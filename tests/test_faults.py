"""Deterministic fault injection: schedule compilation, engine overlay
semantics, proc/app-visible crash effects, and the determinism contract.

The reference has no fault model at all — its packetloss is frozen at
topology load (topology.c:86-105) and a host exists for the whole run.
Here a declarative schedule compiles to dense time-indexed arrays the
jitted window loop indexes with zero Python callbacks, so the matrix
below can assert exact, replayable outcomes: crash-during-transfer,
restart, partition-that-heals, loss spikes, checkpoint/restore straight
through a fault boundary, and bit-identical totals across shard counts.
"""

import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shadow_tpu.analysis.hlo_audit import assert_zero_cost
from shadow_tpu.config import parse_config
from shadow_tpu.core.rng import fault_stream_uniform
from shadow_tpu.core.timebase import SECOND
from shadow_tpu.faults import (
    FaultSpec,
    compile_faults,
    parse_fault_attrs,
    parse_fault_dsl,
)
from shadow_tpu.sim import build_simulation
from shadow_tpu.utils import load_checkpoint, save_checkpoint

TOPO = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="packetloss" attr.type="double" for="edge" id="d4" />
  <key attr.name="latency" attr.type="double" for="edge" id="d3" />
  <key attr.name="bandwidthup" attr.type="int" for="node" id="d2" />
  <key attr.name="bandwidthdown" attr.type="int" for="node" id="d1" />
  <graph edgedefault="undirected">
    <node id="poi-1">
      <data key="d1">10240</data>
      <data key="d2">10240</data>
    </node>
    <edge source="poi-1" target="poi-1">
      <data key="d3">25.0</data>
      <data key="d4">0.0</data>
    </edge>
  </graph>
</graphml>"""


def echo_config(fault: str = "", count: int = 4, stoptime: int = 40,
                recvsize: str = "30KiB") -> str:
    """2-host TGen echo with an optional <fault> element."""
    return textwrap.dedent(f"""\
    <shadow stoptime="{stoptime}">
      <topology><![CDATA[{TOPO}]]></topology>
      <plugin id="tgen" path="tgen"/>
      <host id="server">
        <process plugin="tgen" starttime="1" arguments="server port=8888"/>
      </host>
      <host id="client">
        <process plugin="tgen" starttime="2"
          arguments="peers=server:8888 sendsize=2KiB recvsize={recvsize} count={count} pause=1"/>
      </host>
      {fault}
    </shadow>""")


def _totals(st):
    """The replayable scoreboard: (events, fault drops, quarantined)."""
    return (
        int(jax.device_get(st.stats.n_executed.sum())),
        int(jax.device_get(st.stats.n_fault_dropped.sum())),
        int(jax.device_get(st.stats.n_quarantined.sum())),
    )


# --------------------------------------------------------------- schedule
def test_compile_crash_schedule_timeline():
    spec = FaultSpec(type="crash", hosts="server", start=5.0, end=8.0)
    f = compile_faults([spec], ["server", "client"], 2, seed=1)
    assert f.has_crash and not f.has_link and not f.has_bw
    assert np.array_equal(
        f.alive_at_host(4 * SECOND), np.array([True, True])
    )
    assert np.array_equal(
        f.alive_at_host(6 * SECOND), np.array([False, True])
    )
    assert np.array_equal(
        f.alive_at_host(9 * SECOND), np.array([True, True])
    )
    # downtime accounting: exactly the scheduled window
    dt = f.downtime_in(0, 20 * SECOND)
    assert dt[0] == pytest.approx(3.0)
    assert dt[1] == 0.0
    # liveness flips come out as (t, gid, up) pairs for the proc tier
    assert f.transitions_in(0, 20 * SECOND) == [
        (5 * SECOND, 0, False), (8 * SECOND, 0, True)
    ]


def test_compile_churn_is_seed_deterministic():
    spec = FaultSpec(type="churn", hosts="*", start=2.0, end=30.0,
                     period=10.0, downtime=3.0, frac=0.5)
    names = [f"h{i}" for i in range(8)]
    a = compile_faults([spec], names, 8, seed=9)
    b = compile_faults([spec], names, 8, seed=9)
    c = compile_faults([spec], names, 8, seed=10)
    assert np.array_equal(a.np_alive, b.np_alive)
    assert np.array_equal(a.times, b.times)
    # a different seed picks a different churn set/phase
    assert not np.array_equal(a.np_alive, c.np_alive)
    # frac=0.5 touched about half the hosts, and every host recovers
    ever_down = (~a.np_alive).any(axis=0)
    assert 1 <= int(ever_down.sum()) <= 7
    assert a.np_alive[-1].all() or a.np_alive[0].all()


def test_fault_stream_independent_of_other_draws():
    """Fault draws depend only on (seed, stream, index) — never on how
    many other RNG consumers ran first (the determinism root)."""
    a = fault_stream_uniform(3, 7, 16)
    b = fault_stream_uniform(3, 7, 16)
    assert np.array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(
        np.asarray(a), np.asarray(fault_stream_uniform(4, 7, 16))
    )


def test_fault_dsl_and_xml_attrs_agree():
    dsl = parse_fault_dsl("churn hosts=guard* start=10 end=60 period=20 "
                          "downtime=5 frac=0.2")
    xml = parse_fault_attrs({
        "type": "churn", "hosts": "guard*", "start": "10", "end": "60",
        "period": "20", "downtime": "5", "frac": "0.2",
    })
    assert dsl == xml
    with pytest.raises(ValueError):
        parse_fault_dsl("meteor hosts=*")
    with pytest.raises(ValueError):
        parse_fault_dsl("churn hosts=* start=10 end=5")


def test_config_xml_fault_element_parsed():
    cfg = parse_config(echo_config(
        '<fault type="crash" hosts="server" start="5" end="8"/>'
    ))
    assert len(cfg.faults) == 1
    assert cfg.faults[0].type == "crash"
    assert cfg.faults[0].start == 5.0


# --------------------------------------------------------------- zero cost
def test_faults_off_is_zero_cost():
    """A config with no <fault> element builds the same engine program
    as any other fault-free build — the fault overlay (alive mask,
    routing rescale, epoch sweeps) must vanish from the lowered HLO
    entirely, not just be predicated off. Faults bake into the Engine
    as constants (state only ever carries the always-present
    fault_epoch scalar), so the shared auditor helper runs without a
    state subtree probe."""
    base = build_simulation(parse_config(echo_config()), seed=42)
    off = build_simulation(parse_config(echo_config()), seed=42)
    on = build_simulation(parse_config(echo_config(
        '<fault type="crash" hosts="server" start="5"/>'
    )), seed=42)
    assert base.faults is None and off.faults is None
    assert on.faults is not None
    assert_zero_cost((base.engine, base.state0), (off.engine, off.state0),
                     (on.engine, on.state0), jnp.int64(base.stop_ns))


# ----------------------------------------------------------------- matrix
def test_crash_during_transfer_attributes_losses():
    """The server dies mid-stream and never returns: its pending events
    are quarantined, packets aimed at the corpse are counted as fault
    drops, and the client cannot finish what a fault-free run finishes."""
    base = build_simulation(parse_config(echo_config()), seed=42)
    st0 = base.run()
    assert int(st0.hosts.app.streams_done[base.names.index("client")]) == 4
    _, fd0, q0 = _totals(st0)
    assert fd0 == 0 and q0 == 0  # no schedule, no attribution

    sim = build_simulation(parse_config(echo_config(
        '<fault type="crash" hosts="server" start="5"/>'
    )), seed=42)
    st = sim.run()
    ci = sim.names.index("client")
    _, fd, q = _totals(st)
    assert fd > 0, "packets at the dead host must be attributed"
    assert q > 0, "the crash must void the host's pending events"
    assert int(st.hosts.app.streams_done[ci]) < 4
    # the dead host executes nothing after the crash epoch
    assert sim.faults is not None and sim.faults.has_crash


def test_restart_rebuilds_fresh_state():
    """Crash with an end time: the host comes back re-templated (fresh
    sockets, zeroed counters) and the run completes deterministically."""
    fault = '<fault type="crash" hosts="server" start="5" end="8"/>'
    sims = [build_simulation(parse_config(echo_config(fault)), seed=3)
            for _ in range(2)]
    sts = [s.run() for s in sims]
    t0, t1 = _totals(sts[0]), _totals(sts[1])
    assert t0 == t1, "same seed, same fault timeline, same totals"
    _, fd, q = t0
    assert fd > 0 and q > 0
    # post-restart the server row is the template again at some point:
    # its cumulative socket counters restarted below the pre-crash value
    si = sims[0].names.index("server")
    assert sims[0].faults.alive_at_host(9 * SECOND)[si]
    assert not sims[0].faults.alive_at_host(6 * SECOND)[si]


def test_partition_heals_and_streams_finish():
    """A full partition over [4, 10): nothing crosses while it holds —
    every attempt is a fault drop — then TCP retransmits carry the
    streams to completion after the heal."""
    fault = ('<fault type="partition" src="client" dst="server" '
             'start="4" end="10"/>')
    sim = build_simulation(
        parse_config(echo_config(fault, count=3, stoptime=50)), seed=7
    )
    st = sim.run()
    ci = sim.names.index("client")
    _, fd, q = _totals(st)
    assert fd > 0, "in-partition packets must drop and be attributed"
    assert q == 0, "a partition is not a crash: no events are voided"
    # the streams finish AFTER the heal: retransmission recovered them
    assert int(st.hosts.app.streams_done[ci]) == 3
    assert int(st.hosts.app.t_last_done[ci]) > 10 * SECOND
    retx = int(jax.device_get(st.hosts.net.tcb.n_retx.sum()))
    assert retx > 0


def test_loss_spike_recovers_via_retransmit():
    """A 60% loss spike over [4, 8): drops are attributed to the fault
    overlay, retransmissions recover, all streams still finish."""
    fault = ('<fault type="loss" src="*" dst="*" loss="0.6" '
             'start="4" end="8"/>')
    sim = build_simulation(
        parse_config(echo_config(fault, count=3, stoptime=50)), seed=11
    )
    st = sim.run()
    ci = sim.names.index("client")
    _, fd, q = _totals(st)
    assert fd > 0 and q == 0
    assert int(st.hosts.app.streams_done[ci]) == 3
    assert int(jax.device_get(st.hosts.net.tcb.n_retx.sum())) > 0


def test_checkpoint_restore_through_a_fault(tmp_path):
    """Checkpoint BEFORE the fault fires, restore into a fresh build,
    continue THROUGH the crash: bit-exact with the uninterrupted run —
    the fault timeline is compiled from config+seed, not carried state."""
    fault = '<fault type="crash" hosts="server" start="4" end="7"/>'
    cfg_text = echo_config(fault, count=5, stoptime=20, recvsize="60KiB")

    sim_a = build_simulation(parse_config(cfg_text), seed=5)
    full = sim_a.run(20 * SECOND)

    sim_b = build_simulation(parse_config(cfg_text), seed=5)
    mid = sim_b.run(3 * SECOND)
    path = str(tmp_path / "prefault.npz")
    save_checkpoint(path, mid, meta={"sim_seconds": 3.0})

    sim_c = build_simulation(parse_config(cfg_text), seed=5)
    restored, meta = load_checkpoint(path, sim_c.state0)
    resumed = sim_c.run(20 * SECOND, state=restored)

    for a, b in zip(jax.tree_util.tree_leaves(full),
                    jax.tree_util.tree_leaves(resumed)):
        assert jnp.array_equal(a, b), (
            "restore-through-fault diverged from the straight run"
        )
    _, fd, q = _totals(resumed)
    assert fd > 0 and q > 0  # the fault did fire on the resumed leg


@pytest.mark.slow
def test_fault_totals_identical_across_shard_counts():
    """Acceptance: the same seed produces bit-identical event/drop totals
    on a 1-device build and an 8-device mesh — the fault timeline is a
    pure function of (config, seed), independent of partitioning."""
    from shadow_tpu.parallel.mesh import make_mesh

    hosts = []
    for i in range(8):
        hosts.append(
            f'<host id="server{i}"><process plugin="tgen" starttime="1" '
            'arguments="server port=8888"/></host>'
        )
        hosts.append(
            f'<host id="client{i}"><process plugin="tgen" starttime="2" '
            f'arguments="peers=server{i}:8888 sendsize=2KiB '
            'recvsize=60KiB count=5 pause=1"/></host>'
        )
    cfg_text = textwrap.dedent(f"""\
    <shadow stoptime="40">
      <topology><![CDATA[{TOPO}]]></topology>
      <plugin id="tgen" path="tgen"/>
      {''.join(hosts)}
      <fault type="churn" hosts="server*" start="4" end="20"
             period="8" downtime="2" frac="0.5"/>
    </shadow>""")
    cfg = parse_config(cfg_text)
    st1 = build_simulation(cfg, seed=13).run()
    st8 = build_simulation(cfg, seed=13, mesh=make_mesh(8)).run()
    t1, t8 = _totals(st1), _totals(st8)
    assert t1 == t8
    assert t1[1] > 0, "the churn must actually drop packets in this config"

"""Real-binary tier: compiled C plugins on green threads over device TCP.

The defining capability of the reference (executing real program code
inside the simulation — process.c / rpth / the interposer) in its first
TPU-era slice: a C client/server pair compiled to .so, run as ucontext
green threads by the native runtime, exchanging *actual payload bytes*
through the simulated TCP stack via the window-batched syscall exchange
(SURVEY.md §7 step 6b).

The echo plugin xors the payload, so a passing run proves the byte
content itself crossed both directions intact — not merely that byte
counters advanced.
"""

import os
import shutil
import textwrap

import pytest

from shadow_tpu.config import parse_config

pytestmark = pytest.mark.skipif(
    shutil.which("gcc") is None, reason="no C toolchain"
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TOPO = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="packetloss" attr.type="double" for="edge" id="d4" />
  <key attr.name="latency" attr.type="double" for="edge" id="d3" />
  <key attr.name="bandwidthup" attr.type="int" for="node" id="d2" />
  <key attr.name="bandwidthdown" attr.type="int" for="node" id="d1" />
  <graph edgedefault="undirected">
    <node id="poi-1">
      <data key="d1">10240</data>
      <data key="d2">10240</data>
    </node>
    <edge source="poi-1" target="poi-1">
      <data key="d3">25.0</data>
      <data key="d4">0.0</data>
    </edge>
  </graph>
</graphml>"""


def echo_config(plugin_path: str, nbytes: int) -> str:
    return textwrap.dedent(f"""\
    <shadow stoptime="60">
      <topology><![CDATA[{TOPO}]]></topology>
      <plugin id="shim_echo" path="{plugin_path}"/>
      <host id="server0">
        <process plugin="shim_echo" starttime="1"
          arguments="server 8888 {nbytes}"/>
      </host>
      <host id="client0">
        <process plugin="shim_echo" starttime="2"
          arguments="client server0 8888 {nbytes}"/>
      </host>
    </shadow>""")


@pytest.fixture(scope="module")
def plugin():
    from shadow_tpu.proc.native import compile_plugin

    return compile_plugin(os.path.join(REPO, "native/plugins/shim_echo.c"))


def test_echo_pair_transfers_verified_bytes(plugin):
    from shadow_tpu.proc import ProcessTier

    n = 50_000
    cfg = parse_config(echo_config(plugin, n))
    tier = ProcessTier(cfg, seed=3)
    st = tier.run()

    # both programs ran to completion and verified their payloads
    # (exit code 0 = every recv'd byte matched the expected pattern)
    assert tier.exit_codes == {0: 0, 1: 0}, (tier.exit_codes, tier.logs)
    # the simulated network actually carried the bytes both ways
    rx = st.hosts.net.sockets.rx_bytes.sum()
    assert int(rx) >= 2 * n
    # simtime-tagged plugin logs came out through the runtime
    msgs = [m for (_t, _p, m) in tier.logs]
    assert any("server echoed" in m for m in msgs)
    assert any("client verified" in m for m in msgs)
    tier.close()


def test_echo_pair_sleep_and_time(plugin):
    """sleep_ns suspends on virtual time; time_ns observes it."""
    from shadow_tpu.proc import ProcessTier
    from shadow_tpu.proc.native import compile_plugin

    src = os.path.join(REPO, "native/plugins/_t_sleep.c")
    with open(src, "w") as f:
        f.write(textwrap.dedent("""\
        #include "shim_api.h"
        #include <stdio.h>
        int shim_main(const ShimAPI* a, int argc, char** argv) {
            void* c = a->ctx;
            long long t0 = a->time_ns(c);
            a->sleep_ns(c, 3000000000LL); /* 3 virtual seconds */
            long long t1 = a->time_ns(c);
            char m[64];
            snprintf(m, sizeof m, "slept %lld", t1 - t0);
            a->log_msg(c, m);
            return (t1 - t0 >= 3000000000LL) ? 0 : 1;
        }
        """))
    so = compile_plugin(src, name="_t_sleep")
    cfg = parse_config(textwrap.dedent(f"""\
    <shadow stoptime="30">
      <topology><![CDATA[{TOPO}]]></topology>
      <plugin id="_t_sleep" path="{so}"/>
      <host id="h0">
        <process plugin="_t_sleep" starttime="1" arguments=""/>
      </host>
      <host id="h1">
        <process plugin="_t_sleep" starttime="1" arguments=""/>
      </host>
    </shadow>"""))
    tier = ProcessTier(cfg, seed=0)
    tier.run()
    assert tier.exit_codes == {0: 0, 1: 0}, (tier.exit_codes, tier.logs)
    tier.close()
    os.remove(src)


def clock_config(plugin_path: str, interval_ms: int, ticks: int) -> str:
    return textwrap.dedent(f"""\
    <shadow stoptime="30">
      <topology><![CDATA[{TOPO}]]></topology>
      <plugin id="shim_clock" path="{plugin_path}"/>
      <host id="clocker">
        <process plugin="shim_clock" starttime="1"
          arguments="{interval_ms} {ticks}"/>
      </host>
    </shadow>""")


@pytest.fixture(scope="module")
def clock_plugin():
    from shadow_tpu.proc.native import compile_plugin

    return compile_plugin(os.path.join(REPO, "native/plugins/shim_clock.c"))


def test_timerfd_pipe_poll_surface(clock_plugin):
    """Descriptor-layer syscalls (timer.c / channel.c / poll emulation):
    a periodic timer drives pipe round-trips under poll; every check is
    inside the plugin (exit 0 = timers on the virtual-time grid, pipe
    bytes intact, poll masks and timeout correct, EOF on close)."""
    from shadow_tpu.proc import ProcessTier

    cfg = parse_config(clock_config(clock_plugin, interval_ms=200, ticks=5))
    tier = ProcessTier(cfg, seed=1)
    tier.run()
    assert tier.exit_codes == {0: 0}, (tier.exit_codes, tier.logs)
    assert any("clock done: 5 ticks" in m for _, _, m in tier.logs)
    tier.close()


def test_echo_pair_over_lossy_path(plugin):
    """Real binaries over a lossy link: the in-order device TCP recovers
    every byte, so the native endpoints still verify their payloads
    (the reference's lossy tcp configs, src/test/tcp/CMakeLists.txt)."""
    from shadow_tpu.proc import ProcessTier

    lossy_topo = TOPO.replace(
        '<data key="d4">0.0</data>', '<data key="d4">0.1</data>'
    )
    n = 20_000
    cfg_text = echo_config(plugin, n).replace(TOPO, lossy_topo)
    cfg = parse_config(cfg_text)
    tier = ProcessTier(cfg, seed=11)
    tier.run()
    assert tier.exit_codes == {0: 0, 1: 0}, (tier.exit_codes, tier.logs)
    tier.close()


def test_per_process_stoptime(clock_plugin):
    """<process stoptime>: each process stops individually — two clocks
    on ONE host, one stopped at t=3, the other running to completion
    (the reference's per-process stoptime, configuration.h:38-102; round
    2 rejected differing stoptimes on multi-process hosts outright)."""
    from shadow_tpu.proc import ProcessTier

    cfg = parse_config(textwrap.dedent(f"""\
    <shadow stoptime="30">
      <topology><![CDATA[{TOPO}]]></topology>
      <plugin id="shim_clock" path="{clock_plugin}"/>
      <host id="clocker">
        <process plugin="shim_clock" starttime="1" stoptime="3"
          arguments="500 40"/>
        <process plugin="shim_clock" starttime="1" arguments="500 10"/>
      </host>
    </shadow>"""))
    tier = ProcessTier(cfg, seed=6)
    tier.run()
    # pid 1 (no stoptime) ran its 10 ticks to completion
    assert any("clock done: 10 ticks" in m for _, _, m in tier.logs
               if _ is not None), tier.logs
    assert tier.exit_codes.get(1) == 0
    # pid 0 was stopped at t=3 (~4 ticks of 40) — killed, exit 0 recorded
    assert tier.exit_codes.get(0) == 0
    assert not any("clock done: 40 ticks" in m for _, _, m in tier.logs)
    # no tick message from pid 0 after its stoptime
    late = [t for t, pid, m in tier.logs if pid == 0 and t > 3_100_000_000]
    assert not late, late
    tier.close()

"""TCP end-to-end tests: handshake, bulk transfer, loss recovery, close.

Mirrors the reference's TCP test matrix — {blocking-style apps} x
{lossless, lossy} inside an embedded 2-host topology
(reference: src/test/tcp/CMakeLists.txt:14-60, test_tcp.c) — plus the
determinism-by-diff discipline of src/test/determinism/.
"""

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from shadow_tpu.core.engine import ConstantNetwork, Engine, EngineConfig
from shadow_tpu.core.events import Events
from shadow_tpu.core.timebase import MILLISECOND, SECOND, TIME_INVALID
from shadow_tpu.host.sockets import PROTO_NONE, PROTO_TCP
from shadow_tpu.transport import tcp as tcpm
from shadow_tpu.transport.stack import HostNet, N_PKT_ARGS, SimHost, Stack
from shadow_tpu.transport.tcp import TCP, emit_concat

KIND_APP = tcpm.N_TCP_KINDS  # client: connect + send (+ maybe close)
KIND_APP2 = tcpm.N_TCP_KINDS + 1  # client: second send + close


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class App:
    role: jax.Array  # i32: 0 = client, 1 = server
    rx: jax.Array  # i64 app-delivered bytes
    replied: jax.Array  # bool (request/response mode)
    last_rx: jax.Array  # i64 time of last delivery


def build(total=100_000, reply=0, latency=10 * MILLISECOND, bw=1024.0,
          reliability=1.0, second_send=0, close_after_send=True, seed=7):
    """Host 0 = client connecting to host 1:80 at t=1ms."""
    n_hosts = 2
    tcp = TCP()
    stack = Stack(tcp=tcp)

    def on_recv(hs, slot, pkt, now, key):
        app: App = hs.app
        got = (slot >= 0) & (pkt.length > 0)
        rx = app.rx + jnp.where(got, pkt.length.astype(jnp.int64), 0)
        do_reply = (
            (reply > 0) & (app.role == 1) & (rx >= total) & ~app.replied & got
        )
        app = dataclasses.replace(
            app,
            rx=rx,
            replied=app.replied | do_reply,
            last_rx=jnp.where(got, now, app.last_rx),
        )
        hs = dataclasses.replace(hs, app=app)
        hs, em_s = tcp.send(hs, slot, reply, now, mask=do_reply)
        hs, em_c = tcp.close(hs, slot, now, mask=do_reply)
        return hs, emit_concat(em_s, em_c)

    def on_app(hs, ev: Events, key):
        hs, em1 = tcp.connect(stack, hs, 0, ev.time)
        hs, em2 = tcp.send(hs, 0, total, ev.time)
        hs, em3 = tcp.close(hs, 0, ev.time, mask=close_after_send)
        return hs, emit_concat(em1, em2, em3)

    def on_app2(hs, ev: Events, key):
        hs, em1 = tcp.send(hs, 0, second_send, ev.time, mask=second_send > 0)
        hs, em2 = tcp.close(hs, 0, ev.time, mask=second_send > 0)
        return hs, emit_concat(em1, em2)

    handlers = stack.make_handlers(on_recv) + [on_app, on_app2]
    cfg = EngineConfig(
        n_hosts=n_hosts, capacity=256, lookahead=latency,
        max_emit=tcp.min_max_emit(2), n_args=N_PKT_ARGS, seed=seed,
    )
    eng = Engine(cfg, handlers, ConstantNetwork(latency, reliability))

    net = HostNet.create(n_hosts, 8, bw, bw, with_tcp=True)
    tab = net.sockets.bind(1, 0, PROTO_TCP, 80)
    tab = tab.bind(0, 0, PROTO_TCP, 10_000, peer_host=1, peer_port=80)
    net = dataclasses.replace(net, sockets=tab, tcb=net.tcb.listen(1, 0))
    z = jnp.zeros((n_hosts,), jnp.int64)
    hosts = SimHost(
        net=net,
        app=App(
            role=jnp.arange(n_hosts, dtype=jnp.int32),
            rx=z, replied=jnp.zeros((n_hosts,), bool), last_rx=z,
        ),
    )

    ev = Events.empty((2,), n_args=N_PKT_ARGS)
    times = jnp.asarray(
        [1 * MILLISECOND, 500 * MILLISECOND if second_send else TIME_INVALID],
        jnp.int64,
    )
    ev = dataclasses.replace(
        ev,
        time=times,
        dst=jnp.zeros((2,), jnp.int32),
        src=jnp.zeros((2,), jnp.int32),
        seq=jnp.arange(2, dtype=jnp.int32),
        kind=jnp.asarray([KIND_APP, KIND_APP2], jnp.int32),
    )
    return eng, eng.init_state(hosts, ev)


def test_bulk_transfer_lossless_full_close():
    eng, st = build()
    st = jax.jit(eng.run)(st, jnp.int64(70 * SECOND))
    tcb = st.hosts.net.tcb
    socks = st.hosts.net.sockets
    # all 100k bytes delivered to the server app, exactly once
    assert int(st.hosts.app.rx[1]) == 100_000
    assert int(socks.rx_bytes[1, 1]) == 100_000  # child slot accounting
    # no losses -> no retransmissions anywhere
    assert int(tcb.n_retx.sum()) == 0
    # both endpoints fully closed and their slots freed for reuse
    # (client passes TIME_WAIT -> CLOSED after the 60s close timer,
    # CONFIG_TCPCLOSETIMER_DELAY semantics)
    assert int(tcb.state[0, 0]) == tcpm.CLOSED
    assert int(tcb.state[1, 1]) == tcpm.CLOSED
    assert int(socks.proto[0, 0]) == PROTO_NONE
    assert int(socks.proto[1, 1]) == PROTO_NONE
    # listener still listening
    assert int(tcb.state[1, 0]) == tcpm.LISTEN
    # transfer itself finished quickly (well before the close timer):
    # 100 KiB at 1 MiB/s is ~100 ms of serialization + slow-start ramp
    assert int(st.hosts.app.last_rx[1]) < 2 * SECOND


def test_bulk_transfer_lossy_recovers_all_bytes():
    # 50 KB over ~20 sim-s exercises the same retransmit/ssthresh paths
    # as the original 100 KB/30 s at half the (single-core CI) runtime
    eng, st = build(total=50_000, reliability=0.85, seed=11)
    st = jax.jit(eng.run)(st, jnp.int64(20 * SECOND))
    tcb = st.hosts.net.tcb
    # 15% loss: every byte still arrives, via retransmissions
    assert int(st.hosts.app.rx[1]) == 50_000
    assert int(tcb.n_retx[0, 0]) > 0
    # congestion controller reacted: ssthresh came down from its initial
    assert float(tcb.ssthresh[0, 0]) < tcpm.INIT_SSTHRESH


def test_request_response():
    eng, st = build(total=100, reply=200, close_after_send=False)
    st = jax.jit(eng.run)(st, jnp.int64(70 * SECOND))
    # server got the 100B request, client got the 200B reply
    assert int(st.hosts.app.rx[1]) == 100
    assert int(st.hosts.app.rx[0]) == 200
    # server closed first; auto-close tears the client down too
    tcb = st.hosts.net.tcb
    assert int(tcb.state[0, 0]) == tcpm.CLOSED
    assert int(tcb.state[1, 1]) == tcpm.CLOSED


def test_partial_segment_refill():
    # 100B sent at t=1ms (partial segment), 2000B more at t=500ms: the
    # partial segment is retransmitted with its grown payload and the app
    # sees every byte exactly once
    eng, st = build(total=100, second_send=2000, close_after_send=False)
    st = jax.jit(eng.run)(st, jnp.int64(30 * SECOND))
    assert int(st.hosts.app.rx[1]) == 2100


@pytest.mark.slow  # ~25s retransmission soak; tier-1 keeps the lossless bulk
# transfer, determinism, and close-path pins for the same stack
def test_heavy_loss_request_response_recovers():
    """Regression: server-side (passive-open) connections must own an RTO
    timer — with 30% loss the server's reply/FIN retransmits from the
    child slot or the exchange hangs forever."""
    for seed in (1, 2, 4):
        eng, st = build(
            total=100, reply=5000, reliability=0.7, close_after_send=False,
            seed=seed,
        )
        st = jax.jit(eng.run)(st, jnp.int64(120 * SECOND))
        assert int(st.hosts.app.rx[0]) == 5000, f"seed {seed}"
        assert int(st.hosts.app.rx[1]) == 100, f"seed {seed}"
        tcb = st.hosts.net.tcb
        assert int(tcb.state[0, 0]) == tcpm.CLOSED, f"seed {seed}"
        assert int(tcb.state[1, 1]) == tcpm.CLOSED, f"seed {seed}"


def test_rtt_estimator_converges():
    eng, st = build()
    st = jax.jit(eng.run)(st, jnp.int64(5 * SECOND))
    srtt = int(st.hosts.net.tcb.srtt[0, 0])
    # path RTT is 2*10ms + serialization; srtt must be in that ballpark
    assert 15 * MILLISECOND < srtt < 200 * MILLISECOND


def test_determinism_two_runs_identical():
    eng, st = build(reliability=0.9, seed=13)
    run = jax.jit(eng.run)
    a = run(st, jnp.int64(10 * SECOND))
    b = run(st, jnp.int64(10 * SECOND))
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        assert jnp.array_equal(x, y)

"""Checkpoint/resume: bit-exact continuation of a simulation.

A capability the reference lacks entirely (SURVEY.md §5): because the full
simulation state is one pytree, save -> rebuild -> load -> continue must
reproduce the uninterrupted run exactly, down to RNG counters and event
queue contents.
"""

import jax
import jax.numpy as jnp
import pytest

from shadow_tpu.config import parse_config
from shadow_tpu.core.timebase import SECOND
from shadow_tpu.sim import build_simulation
from shadow_tpu.utils import load_checkpoint, save_checkpoint

CONFIG = """<shadow stoptime="10">
  <topology>
    <![CDATA[<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
      <key attr.name="latency" attr.type="double" for="edge" id="d3" />
      <key attr.name="bandwidthup" attr.type="int" for="node" id="d2" />
      <key attr.name="bandwidthdown" attr.type="int" for="node" id="d1" />
      <graph edgedefault="undirected">
        <node id="poi-1">
          <data key="d1">2048</data>
          <data key="d2">2048</data>
        </node>
        <edge source="poi-1" target="poi-1">
          <data key="d3">50.0</data>
        </edge>
      </graph>
    </graphml>]]>
  </topology>
  <plugin id="phold" path="shadow-plugin-test-phold.so" />
  <host id="peer" quantity="6">
    <process plugin="phold" starttime="1" arguments="basename=peer quantity=6 load=4" />
  </host>
</shadow>"""


def _build():
    return build_simulation(parse_config(CONFIG), seed=7)


def test_checkpoint_roundtrip_bit_exact(tmp_path):
    path = str(tmp_path / "ckpt.npz")

    # uninterrupted run to 10s
    sim_a = _build()
    full = sim_a.run(10 * SECOND)

    # interrupted: run to 4s, checkpoint, rebuild fresh, resume to 10s
    sim_b = _build()
    mid = sim_b.run(4 * SECOND)
    save_checkpoint(path, mid, meta={"sim_seconds": 4.0})

    sim_c = _build()
    restored, meta = load_checkpoint(path, sim_c.state0)
    assert meta["sim_seconds"] == 4.0
    resumed = sim_c.run(10 * SECOND, state=restored)

    flat_a = jax.tree_util.tree_leaves(full)
    flat_b = jax.tree_util.tree_leaves(resumed)
    assert len(flat_a) == len(flat_b)
    for a, b in zip(flat_a, flat_b):
        assert a.dtype == b.dtype and a.shape == b.shape
        assert jnp.array_equal(a, b), "resumed state diverged from straight run"


def test_checkpoint_structural_mismatch_rejected(tmp_path):
    path = str(tmp_path / "ckpt.npz")
    sim = _build()
    save_checkpoint(path, sim.state0)

    other = build_simulation(parse_config(CONFIG), seed=7, n_sockets=4)
    with pytest.raises(ValueError):
        load_checkpoint(path, other.state0)


# ---------------------------------------------------------------------------
# Integrity + rotation mechanics need no simulator: any pytree works, and
# a plain dict keeps these tests millisecond-fast.

import json  # noqa: E402
import os  # noqa: E402

import numpy as np  # noqa: E402

from shadow_tpu.utils import (  # noqa: E402
    checkpoint_generations,
    find_resume_checkpoint,
    verify_checkpoint,
)


def _tree():
    return {
        "a": jnp.arange(64, dtype=jnp.int64),
        "b": jnp.linspace(0.0, 1.0, 32, dtype=jnp.float32),
    }


def test_checkpoint_crc_detects_bit_flip(tmp_path):
    """A flipped payload bit that keeps the zip container intact must
    still be caught: per-leaf CRCs, not just np.load succeeding."""
    path = str(tmp_path / "ck.npz")
    save_checkpoint(path, _tree(), meta={"k": 1})

    with np.load(path, allow_pickle=False) as z:
        arrays = {k: z[k].copy() for k in z.files}
    leaf = arrays["leaf_0"]
    leaf.flat[3] ^= 1  # single bit flip, same shape/dtype
    np.savez(path, **arrays)  # header (with original CRCs) unchanged

    with pytest.raises(ValueError, match="(?i)crc"):
        verify_checkpoint(path)
    with pytest.raises(ValueError, match="(?i)crc"):
        load_checkpoint(path, _tree())


def test_checkpoint_truncated_file_is_clear_error(tmp_path):
    """Satellite: a truncated/corrupt .npz (killed mid-write without the
    atomic rename, disk full, ...) must raise a ValueError naming the
    file — not leak BadZipFile/KeyError out of numpy internals."""
    path = str(tmp_path / "ck.npz")
    save_checkpoint(path, _tree())
    blob = open(path, "rb").read()
    open(path, "wb").write(blob[: len(blob) // 2])

    with pytest.raises(ValueError, match="ck.npz"):
        load_checkpoint(path, _tree())
    with pytest.raises(ValueError, match="truncated or corrupt"):
        verify_checkpoint(path)

    # a non-archive file (e.g. some stray artifact) reads the same way
    open(path, "wb").write(b"not a checkpoint")
    with pytest.raises(ValueError, match="truncated or corrupt"):
        verify_checkpoint(path)


def test_checkpoint_header_missing_is_clear_error(tmp_path):
    path = str(tmp_path / "ck.npz")
    save_checkpoint(path, _tree())
    with np.load(path, allow_pickle=False) as z:
        arrays = {k: z[k].copy() for k in z.files if k != "__header__"}
    np.savez(path, **arrays)
    with pytest.raises(ValueError, match="truncated or corrupt"):
        verify_checkpoint(path)


def test_checkpoint_rotation_keeps_n_generations(tmp_path):
    path = str(tmp_path / "ck.npz")
    for i in range(4):
        save_checkpoint(path, _tree(), meta={"gen": i}, keep=2)

    gens = checkpoint_generations(path)
    assert gens == [path, path + ".1"]
    assert not os.path.exists(path + ".2")  # pruned beyond the horizon
    assert verify_checkpoint(path)["gen"] == 3  # newest at the bare path
    assert verify_checkpoint(path + ".1")["gen"] == 2


def test_resume_auto_falls_back_past_corrupt_newest(tmp_path):
    """Satellite: --resume auto must skip a truncated newest generation
    and pick the older one that still verifies."""
    path = str(tmp_path / "ck.npz")
    save_checkpoint(path, _tree(), meta={"gen": 0}, keep=3)
    save_checkpoint(path, _tree(), meta={"gen": 1}, keep=3)
    blob = open(path, "rb").read()
    open(path, "wb").write(blob[:100])  # newest is now garbage

    chosen, meta, skipped = find_resume_checkpoint(path)
    assert chosen == path + ".1"
    assert meta["gen"] == 0
    assert [p for p, _ in skipped] == [path]

    # no generation at all -> None (caller prints its own error)
    assert find_resume_checkpoint(str(tmp_path / "absent.npz")) is None

    # every generation corrupt -> ValueError listing each candidate
    open(path + ".1", "wb").write(b"junk")
    with pytest.raises(ValueError, match="no verifiable checkpoint"):
        find_resume_checkpoint(path)


def test_checkpoint_format_v3_still_loads(tmp_path):
    """Pre-CRC checkpoints (format 3) stay loadable: strip the crc32
    field and downgrade the version marker, as an old writer would have
    produced."""
    path = str(tmp_path / "ck.npz")
    save_checkpoint(path, _tree(), meta={"old": True})
    with np.load(path, allow_pickle=False) as z:
        arrays = {k: z[k].copy() for k in z.files}
    header = json.loads(bytes(arrays["__header__"]).decode())
    header["format_version"] = 3
    del header["crc32"]
    arrays["__header__"] = np.frombuffer(
        json.dumps(header).encode(), dtype=np.uint8
    )
    np.savez(path, **arrays)

    tree, meta = load_checkpoint(path, _tree())
    assert meta == {"old": True}
    assert jnp.array_equal(tree["a"], _tree()["a"])

    # ...but an unknown future version is refused
    header["format_version"] = 99
    arrays["__header__"] = np.frombuffer(
        json.dumps(header).encode(), dtype=np.uint8
    )
    np.savez(path, **arrays)
    with pytest.raises(ValueError, match="format"):
        load_checkpoint(path, _tree())

"""Checkpoint/resume: bit-exact continuation of a simulation.

A capability the reference lacks entirely (SURVEY.md §5): because the full
simulation state is one pytree, save -> rebuild -> load -> continue must
reproduce the uninterrupted run exactly, down to RNG counters and event
queue contents.
"""

import jax
import jax.numpy as jnp
import pytest

from shadow_tpu.config import parse_config
from shadow_tpu.core.timebase import SECOND
from shadow_tpu.sim import build_simulation
from shadow_tpu.utils import load_checkpoint, save_checkpoint

CONFIG = """<shadow stoptime="10">
  <topology>
    <![CDATA[<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
      <key attr.name="latency" attr.type="double" for="edge" id="d3" />
      <key attr.name="bandwidthup" attr.type="int" for="node" id="d2" />
      <key attr.name="bandwidthdown" attr.type="int" for="node" id="d1" />
      <graph edgedefault="undirected">
        <node id="poi-1">
          <data key="d1">2048</data>
          <data key="d2">2048</data>
        </node>
        <edge source="poi-1" target="poi-1">
          <data key="d3">50.0</data>
        </edge>
      </graph>
    </graphml>]]>
  </topology>
  <plugin id="phold" path="shadow-plugin-test-phold.so" />
  <host id="peer" quantity="6">
    <process plugin="phold" starttime="1" arguments="basename=peer quantity=6 load=4" />
  </host>
</shadow>"""


def _build():
    return build_simulation(parse_config(CONFIG), seed=7)


def test_checkpoint_roundtrip_bit_exact(tmp_path):
    path = str(tmp_path / "ckpt.npz")

    # uninterrupted run to 10s
    sim_a = _build()
    full = sim_a.run(10 * SECOND)

    # interrupted: run to 4s, checkpoint, rebuild fresh, resume to 10s
    sim_b = _build()
    mid = sim_b.run(4 * SECOND)
    save_checkpoint(path, mid, meta={"sim_seconds": 4.0})

    sim_c = _build()
    restored, meta = load_checkpoint(path, sim_c.state0)
    assert meta["sim_seconds"] == 4.0
    resumed = sim_c.run(10 * SECOND, state=restored)

    flat_a = jax.tree_util.tree_leaves(full)
    flat_b = jax.tree_util.tree_leaves(resumed)
    assert len(flat_a) == len(flat_b)
    for a, b in zip(flat_a, flat_b):
        assert a.dtype == b.dtype and a.shape == b.shape
        assert jnp.array_equal(a, b), "resumed state diverged from straight run"


def test_checkpoint_structural_mismatch_rejected(tmp_path):
    path = str(tmp_path / "ckpt.npz")
    sim = _build()
    save_checkpoint(path, sim.state0)

    other = build_simulation(parse_config(CONFIG), seed=7, n_sockets=4)
    with pytest.raises(ValueError):
        load_checkpoint(path, other.state0)

"""Supervised runs: watchdog, graceful shutdown, invariant guard.

Fast lane: unit tests drive the runtime layer in-process — the watchdog
with an injected exit so a firing is observable instead of fatal, the
supervisor's signal handlers via os.kill on our own pid, the invariant
checker on a real mid-run EngineState and on deliberately corrupted
copies of it.

Slow lane (subprocess, `-m slow`): the two acceptance scenarios from
the issue — SIGTERM mid-run must leave a CRC-verified checkpoint whose
resumed continuation is bit-identical to an uninterrupted run, and a
native plugin spinning inside shim_main must be detected by the
watchdog, which exits 75 with a diagnostic bundle instead of hanging
until the outer CI timeout.
"""

import json
import os
import shutil
import signal
import subprocess
import sys
import textwrap
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --------------------------------------------------------------- watchdog


def test_watchdog_rejects_nonpositive_timeout():
    from shadow_tpu.runtime import Watchdog

    with pytest.raises(ValueError):
        Watchdog(0.0)


def test_watchdog_fires_and_writes_bundle(tmp_path):
    from shadow_tpu.runtime import EXIT_STALL, Watchdog

    codes: list[int] = []
    wd = Watchdog(
        0.3, diag_dir=str(tmp_path), label="t",
        info=lambda: {"live_pids": [11, 12]},
        _exit=codes.append, _stream=open(os.devnull, "w"),
    )
    wd.pet(now_ns=123, windows=7)
    wd.start()
    deadline = time.monotonic() + 10.0
    while not codes and time.monotonic() < deadline:
        time.sleep(0.05)
    wd.stop()
    assert codes == [EXIT_STALL]
    assert wd.fired

    base = tmp_path / f"t.stall.{os.getpid()}"
    stacks = (base.parent / (base.name + ".stacks.txt")).read_text(
        errors="replace"
    )
    assert "Thread" in stacks  # faulthandler dumped every thread
    bundle = json.loads((base.parent / (base.name + ".json")).read_text())
    assert bundle["exit_code"] == EXIT_STALL
    assert bundle["stalled_for_s"] >= 0.3
    assert bundle["progress"]["now_ns"] == 123
    assert bundle["progress"]["windows"] == 7
    assert bundle["live_pids"] == [11, 12]


def test_watchdog_pet_keeps_alive(tmp_path):
    from shadow_tpu.runtime import Watchdog

    codes: list[int] = []
    wd = Watchdog(0.5, diag_dir=str(tmp_path), _exit=codes.append)
    wd.start()
    for _ in range(15):  # 1.5s of petting, 3x the deadline
        time.sleep(0.1)
        wd.pet()
    assert wd.margin_s() > 0
    wd.stop()
    assert codes == [] and not wd.fired


def test_watchdog_bundle_survives_broken_info(tmp_path):
    from shadow_tpu.runtime import Watchdog

    codes: list[int] = []

    def bad_info():
        raise RuntimeError("info source is the broken part")

    wd = Watchdog(0.2, diag_dir=str(tmp_path), label="b", info=bad_info,
                  _exit=codes.append, _stream=open(os.devnull, "w"))
    wd.start()
    deadline = time.monotonic() + 10.0
    while not codes and time.monotonic() < deadline:
        time.sleep(0.05)
    wd.stop()
    bundle = json.loads(
        (tmp_path / f"b.stall.{os.getpid()}.json").read_text()
    )
    assert "info_error" in bundle


# ------------------------------------------------------------- supervisor


def test_signal_exit_codes():
    from shadow_tpu.runtime import signal_exit_code

    assert signal_exit_code(signal.SIGTERM) == 143
    assert signal_exit_code(signal.SIGINT) == 130


def test_supervisor_sigusr1_one_shot(capsys):
    from shadow_tpu.runtime import Supervisor

    with Supervisor() as sup:
        assert not sup.take_checkpoint_request()
        os.kill(os.getpid(), signal.SIGUSR1)
        deadline = time.monotonic() + 5.0
        while not sup._ckpt_requested and time.monotonic() < deadline:
            time.sleep(0.01)
        assert sup.take_checkpoint_request()
        assert not sup.take_checkpoint_request()  # drained
        assert not sup.stop_requested


def test_supervisor_sigterm_requests_stop(capsys):
    from shadow_tpu.runtime import Supervisor

    before = signal.getsignal(signal.SIGTERM)
    with Supervisor() as sup:
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.monotonic() + 5.0
        while not sup.stop_requested and time.monotonic() < deadline:
            time.sleep(0.01)
        assert sup.stop_requested
        assert sup.exit_code() == 143
        # one-shot escalation: the next SIGTERM would get the default
        # (fatal) disposition, so a wedged shutdown is still killable
        assert signal.getsignal(signal.SIGTERM) == signal.SIG_DFL
    # leaving the context restores whatever pytest had installed
    assert signal.getsignal(signal.SIGTERM) == before


# ------------------------------------------------------------- invariants

CONFIG = """<shadow stoptime="10">
  <topology>
    <![CDATA[<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
      <key attr.name="latency" attr.type="double" for="edge" id="d3" />
      <key attr.name="bandwidthup" attr.type="int" for="node" id="d2" />
      <key attr.name="bandwidthdown" attr.type="int" for="node" id="d1" />
      <graph edgedefault="undirected">
        <node id="poi-1">
          <data key="d1">2048</data>
          <data key="d2">2048</data>
        </node>
        <edge source="poi-1" target="poi-1">
          <data key="d3">50.0</data>
        </edge>
      </graph>
    </graphml>]]>
  </topology>
  <plugin id="phold" path="shadow-plugin-test-phold.so" />
  <host id="peer" quantity="6">
    <process plugin="phold" starttime="1" arguments="basename=peer quantity=6 load=4" />
  </host>
</shadow>"""


@pytest.fixture(scope="module")
def mid_state():
    from shadow_tpu.config import parse_config
    from shadow_tpu.core.timebase import SECOND
    from shadow_tpu.sim import build_simulation

    sim = build_simulation(parse_config(CONFIG), seed=7)
    return sim.run(2 * SECOND)


def test_invariants_pass_on_real_state(mid_state):
    from shadow_tpu.runtime.invariants import check_state, validate

    assert check_state(mid_state) == []
    now = validate(mid_state)
    assert now >= 2_000_000_000
    # and the clock threads through as the next prev_now
    assert validate(mid_state, prev_now=now) == now


def test_invariants_catch_clock_regression(mid_state):
    import dataclasses

    import jax.numpy as jnp

    from shadow_tpu.runtime.invariants import InvariantViolation, validate

    bad = dataclasses.replace(
        mid_state, now=jnp.asarray(-5, mid_state.now.dtype)
    )
    with pytest.raises(InvariantViolation, match="negative clock"):
        validate(bad)
    with pytest.raises(InvariantViolation, match="backwards"):
        validate(mid_state, prev_now=int(1e18))


def test_invariants_catch_unsorted_queue(mid_state):
    import dataclasses

    import jax
    import jax.numpy as jnp

    from shadow_tpu.runtime.invariants import InvariantViolation, validate

    t = jax.device_get(mid_state.queues.time).copy()
    # find a host with >= 2 live events and swap-break its time order
    from shadow_tpu.core.timebase import TIME_INVALID

    live = (t != TIME_INVALID).sum(axis=1)
    h = int(live.argmax())
    assert live[h] >= 2, "phold run should leave queued events"
    t[h, 0], t[h, 1] = t[h, 1] + 1, t[h, 0]
    bad = dataclasses.replace(
        mid_state,
        queues=dataclasses.replace(
            mid_state.queues, time=jnp.asarray(t)
        ),
    )
    with pytest.raises(InvariantViolation, match="order"):
        validate(bad)


def test_invariants_catch_empty_slot_ahead(mid_state):
    import dataclasses

    import jax
    import jax.numpy as jnp

    from shadow_tpu.core.timebase import TIME_INVALID
    from shadow_tpu.runtime.invariants import InvariantViolation, validate

    t = jax.device_get(mid_state.queues.time).copy()
    live = (t != TIME_INVALID).sum(axis=1)
    h = int(live.argmax())
    t[h, 0] = TIME_INVALID  # hole ahead of live rows
    bad = dataclasses.replace(
        mid_state,
        queues=dataclasses.replace(
            mid_state.queues, time=jnp.asarray(t)
        ),
    )
    with pytest.raises(InvariantViolation, match="empties-last"):
        validate(bad)


def test_invariants_catch_negative_counter(mid_state):
    import dataclasses

    import jax.numpy as jnp

    from shadow_tpu.runtime.invariants import InvariantViolation, validate

    bad = dataclasses.replace(
        mid_state,
        src_seq=jnp.full_like(mid_state.src_seq, -3),
    )
    with pytest.raises(InvariantViolation, match="negative counter"):
        validate(bad)


def test_invariants_catch_nan(mid_state):
    import jax
    import jax.numpy as jnp

    from shadow_tpu.runtime.invariants import InvariantViolation, validate

    leaves, treedef = jax.tree_util.tree_flatten(mid_state)
    idx = next(
        (i for i, l in enumerate(leaves)
         if jnp.issubdtype(l.dtype, jnp.floating)),
        None,
    )
    if idx is None:
        pytest.skip("EngineState has no float leaves")
    leaves = list(leaves)
    leaves[idx] = jnp.full_like(leaves[idx], jnp.nan)
    bad = jax.tree_util.tree_unflatten(treedef, leaves)
    with pytest.raises(InvariantViolation, match="non-finite"):
        validate(bad)


@pytest.mark.slow  # ~10s CLI subprocess; the invariant-guard unit pins above
# cover the checks themselves in-process
def test_cli_validate_flag_passes_clean_run(tmp_path):
    # end-to-end: --validate on a healthy run must not trip (exercises
    # the every-K-windows cadence inside the real driver loop)
    from shadow_tpu.cli import main

    rc = main(["--test", "--stoptime", "2", "--validate", "3",
               "--heartbeat-frequency", "1",
               "--checkpoint-path", str(tmp_path / "ck.npz")])
    assert rc == 0


# ------------------------------------------- subprocess acceptance (slow)


def _cli_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # share the suite's persistent compile cache so the subprocess pays
    # ~no XLA compile time after the first ever run on this machine
    env["JAX_COMPILATION_CACHE_DIR"] = os.path.join(REPO, ".jax_cache_cpu")
    env["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = "0"
    env["JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES"] = "0"
    return env


def _wait_for(predicate, timeout_s, what):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.25)
    raise AssertionError(f"timed out after {timeout_s}s waiting for {what}")


@pytest.mark.slow
def test_sigterm_midrun_checkpoints_and_resumes_bit_exact(tmp_path):
    """Issue acceptance: SIGTERM mid-run -> CRC-verified checkpoint;
    resuming it and running to T is bit-identical to an uninterrupted
    run to T."""
    import jax
    import jax.numpy as jnp

    from shadow_tpu.utils import load_checkpoint, verify_checkpoint

    cfg_path = tmp_path / "phold.config.xml"
    cfg_path.write_text(CONFIG)
    ck = str(tmp_path / "ck.npz")
    base = [sys.executable, "-m", "shadow_tpu", str(cfg_path),
            "--seed", "7", "--checkpoint-path", ck]

    # long stoptime + short batches: the run will never finish on its
    # own; we interrupt as soon as the first interval checkpoint lands
    p = subprocess.Popen(
        base + ["--stoptime", "3600", "--heartbeat-frequency", "0.5",
                "--checkpoint-interval", "1", "--checkpoint-keep", "3"],
        cwd=REPO, env=_cli_env(),
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True,
    )
    try:
        _wait_for(lambda: os.path.exists(ck), 240,
                  "first interval checkpoint")
        time.sleep(1.0)
        p.send_signal(signal.SIGTERM)
        rc = p.wait(timeout=120)
    finally:
        if p.poll() is None:
            p.kill()
            p.wait()
    stderr = p.stderr.read()
    assert rc == 143, f"expected 128+SIGTERM, got {rc}\n{stderr}"
    assert "will checkpoint and exit" in stderr

    meta = verify_checkpoint(ck)  # every leaf CRC must hold
    assert meta["interrupted"] == int(signal.SIGTERM)
    t0 = float(meta["sim_seconds"])
    assert t0 > 0
    stop = int(t0) + 2

    # resume to `stop`; the interval cadence is absolute, so the final
    # checkpoint lands exactly at sim time `stop`
    r = subprocess.run(
        base + ["--stoptime", str(stop), "--resume", "auto",
                "--checkpoint-interval", "1", "--checkpoint-keep", "3"],
        cwd=REPO, env=_cli_env(), capture_output=True, text=True,
        timeout=600,
    )
    assert r.returncode == 0, r.stderr
    assert f"resumed from {ck}" in r.stderr
    meta2 = verify_checkpoint(ck)
    assert float(meta2["sim_seconds"]) == float(stop)

    # uninterrupted reference run, in-process (shares the compile cache)
    from shadow_tpu.config import parse_config
    from shadow_tpu.core.timebase import SECOND
    from shadow_tpu.sim import build_simulation

    sim = build_simulation(parse_config(str(cfg_path)), seed=7)
    straight = sim.run(stop * SECOND)
    resumed, _ = load_checkpoint(ck, sim.state0)

    flat_a = jax.tree_util.tree_leaves(straight)
    flat_b = jax.tree_util.tree_leaves(resumed)
    assert len(flat_a) == len(flat_b)
    for a, b in zip(flat_a, flat_b):
        assert jnp.array_equal(a, b), (
            "interrupt+resume diverged from the uninterrupted run"
        )


SPIN_PLUGIN = textwrap.dedent("""\
    /* pathological plugin: never yields, never returns — the hang class
       the watchdog exists for (a cooperative green thread that spins
       blocks shim_pump, and with it the whole driver, forever). */
    #include "shim_api.h"

    int shim_main(const ShimAPI* api, int argc, char** argv) {
        (void)api; (void)argc; (void)argv;
        for (;;) { }
        return 0;
    }
""")


@pytest.mark.slow
@pytest.mark.skipif(shutil.which("gcc") is None, reason="no C toolchain")
def test_watchdog_detects_hung_plugin(tmp_path):
    """Issue acceptance: a plugin spinning in shim_main stalls the proc
    tier; the watchdog must abort with the stall exit code and leave a
    diagnostic bundle within the deadline."""
    from shadow_tpu.proc.native import compile_plugin

    src = tmp_path / "shim_spin.c"
    src.write_text(SPIN_PLUGIN)
    so = compile_plugin(str(src), name="_t_spin")

    topo = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
      <key attr.name="latency" attr.type="double" for="edge" id="d3" />
      <key attr.name="bandwidthup" attr.type="int" for="node" id="d2" />
      <key attr.name="bandwidthdown" attr.type="int" for="node" id="d1" />
      <graph edgedefault="undirected">
        <node id="poi-1">
          <data key="d1">2048</data><data key="d2">2048</data>
        </node>
        <edge source="poi-1" target="poi-1">
          <data key="d3">25.0</data>
        </edge>
      </graph>
    </graphml>"""
    cfg_path = tmp_path / "spin.config.xml"
    cfg_path.write_text(textwrap.dedent(f"""\
        <shadow stoptime="30">
          <topology><![CDATA[{topo}]]></topology>
          <plugin id="spin" path="{so}"/>
          <host id="h0">
            <process plugin="spin" starttime="1" arguments=""/>
          </host>
        </shadow>"""))

    diag = tmp_path / "diag"
    # deadline must absorb one cold XLA compile of the proc-tier engine;
    # with the shared persistent cache this is normally seconds
    p = subprocess.run(
        [sys.executable, "-m", "shadow_tpu", str(cfg_path),
         "--watchdog", "60", "--diag-dir", str(diag)],
        cwd=REPO, env=_cli_env(), capture_output=True, text=True,
        timeout=540,
    )
    assert p.returncode == 75, (
        f"expected stall exit code 75, got {p.returncode}\n"
        f"stdout: {p.stdout}\nstderr: {p.stderr}"
    )
    bundles = list(diag.glob("*.stall.*.json"))
    stacks = list(diag.glob("*.stall.*.stacks.txt"))
    assert bundles and stacks, f"missing diagnostics in {diag}"
    bundle = json.loads(bundles[0].read_text())
    assert bundle["exit_code"] == 75
    assert "STALL" in p.stderr

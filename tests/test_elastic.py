"""Elastic shard recovery (docs/13-Elastic-Recovery.md).

Fast lane, in-process (conftest forces 8 virtual CPU devices, so every
mesh size up to 8 is available in tier-1):

- checkpoint format v6 migration: v5 files (no mesh identity) still
  load; `read_header_info` reports the stored mesh;
- reshard-on-resume bit-identity: a checkpoint written at 8 shards
  resumes through 4 shards down to 1 — and 1 back up to 8 — with every
  mesh-portable leaf bit-identical to the uninterrupted single-device
  run (the `.xchg` exchange buffer and the cross-shard telemetry
  counters are the only mesh-shaped state, and are excluded);
- the refusal paths: in-flight exchange events, sharded spill;
- atomic checkpoint IO: transient ENOSPC retries with backoff, and a
  hard failure that must leave the previous generation intact;
- `find_resume_checkpoint` candidates: the `.emergency` crash file and
  all-or-none sharded sets;
- the collective-stall Watchdog: peerlost bundle kind, compile-grace
  re-arming, exit-code taxonomy, `next_retry_argv` / `run_with_retry`
  with injected process control;
- zero-cost: the elastic plumbing (explicit `host_order`) leaves the
  lowered HLO byte-identical when it is a no-op.

Slow lane (subprocess, `-m slow`): the two chaos acceptance scenarios —
a wedged collective must exit 77 with a per-shard diagnostic bundle,
and the same failure under `--retry` must recover on a shrunken mesh to
a bit-identical summary.
"""

import glob
import json
import os
import signal
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shadow_tpu.config import parse_config
from shadow_tpu.core.timebase import SECOND
from shadow_tpu.parallel import mesh as pmesh
from shadow_tpu.sim import build_simulation
from shadow_tpu.utils import (
    find_resume_checkpoint,
    load_checkpoint,
    save_checkpoint,
    verify_checkpoint,
)
from shadow_tpu.utils import checkpoint as ckpt_mod
from shadow_tpu.utils.checkpoint import (
    _leaf_paths,
    read_header_info,
    shard_member_path,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# 16 hosts: divisible by every mesh size in the 8 -> 4 -> 1 -> 8 chain
CONFIG = """<shadow stoptime="10">
  <topology>
    <![CDATA[<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
      <key attr.name="latency" attr.type="double" for="edge" id="d3" />
      <key attr.name="bandwidthup" attr.type="int" for="node" id="d2" />
      <key attr.name="bandwidthdown" attr.type="int" for="node" id="d1" />
      <graph edgedefault="undirected">
        <node id="poi-1">
          <data key="d1">2048</data>
          <data key="d2">2048</data>
        </node>
        <edge source="poi-1" target="poi-1">
          <data key="d3">50.0</data>
        </edge>
      </graph>
    </graphml>]]>
  </topology>
  <plugin id="phold" path="shadow-plugin-test-phold.so" />
  <host id="peer" quantity="16">
    <process plugin="phold" starttime="1" arguments="basename=peer quantity=16 load=4" />
  </host>
</shadow>"""


def _build(n_shards=1):
    mesh = pmesh.make_mesh(n_shards) if n_shards > 1 else None
    return build_simulation(parse_config(CONFIG), seed=7, mesh=mesh)


def _mesh_info(sim):
    return {
        "n_shards": (int(sim.mesh.devices.size)
                     if sim.mesh is not None else 1),
        "dcn_slices": 1,
        "host_order": (list(sim.host_order)
                       if sim.host_order is not None else None),
    }


# The exchange buffer and the scheduling telemetry counters are the
# only mesh-shaped state; everything else must survive a reshard
# bit-for-bit (ISSUE acceptance — mirrors bench.py CHAOS_CMP_KEYS).
# n_inner_steps counts per-shard drain substeps: each shard drains its
# own slice, so the global total grows with the shard count even when
# every event executes identically.
_MESH_TELEMETRY = ("n_cross_shard", "n_xchg_rounds", "n_inner_steps")


def _portable_leaves(state):
    out = {}
    for pth, leaf in zip(_leaf_paths(state), jax.tree_util.tree_leaves(state)):
        if pth.startswith(".xchg"):
            continue
        if any(t in pth for t in _MESH_TELEMETRY):
            continue
        out[pth] = np.asarray(jax.device_get(leaf))
    return out


def _assert_portable_equal(got, want, label):
    assert got.keys() == want.keys(), (
        f"{label}: portable leaf sets differ: "
        f"{sorted(got.keys() ^ want.keys())}")
    for pth in want:
        assert np.array_equal(got[pth], want[pth]), (
            f"{label}: leaf {pth} diverged from the uninterrupted run")


@pytest.fixture(scope="module")
def straight():
    """Uninterrupted single-device reference run to 10s."""
    sim = _build(1)
    final = sim.run(10 * SECOND)
    return _portable_leaves(final)


# ----------------------------------------------------------- v6 format


def _tree():
    return {
        "a": jnp.arange(64, dtype=jnp.int64),
        "b": jnp.linspace(0.0, 1.0, 32, dtype=jnp.float32),
    }


def _rewrite_header(path, mutate):
    with np.load(path, allow_pickle=False) as z:
        arrays = {k: z[k].copy() for k in z.files}
    header = json.loads(bytes(arrays["__header__"]).decode())
    mutate(header)
    arrays["__header__"] = np.frombuffer(
        json.dumps(header).encode(), dtype=np.uint8)
    np.savez(path, **arrays)


def test_checkpoint_format_v5_still_loads(tmp_path):
    """A v5 file (pre-mesh-identity) loads, reports mesh=None, and the
    reshard flag degrades gracefully on it."""
    path = str(tmp_path / "ck.npz")
    save_checkpoint(path, _tree(), meta={"sim_seconds": 2.0})

    def downgrade(header):
        header["format_version"] = 5
        header.pop("mesh", None)
        header.pop("xchg_empty", None)
        header.pop("shard", None)

    _rewrite_header(path, downgrade)

    info = read_header_info(path)
    assert info["format_version"] == 5
    assert info["mesh"] is None
    assert info["shard"] is None
    assert info["xchg_empty"] is True  # pre-v6 writers never had one

    tree, meta = load_checkpoint(path, _tree(), reshard=True)
    assert meta == {"sim_seconds": 2.0}
    assert jnp.array_equal(tree["a"], _tree()["a"])


def test_header_records_mesh_identity(tmp_path):
    path = str(tmp_path / "ck.npz")
    save_checkpoint(
        path, _tree(),
        mesh_info={"n_shards": 8, "dcn_slices": 2, "host_order": [1, 0]},
    )
    info = read_header_info(path)
    assert info["format_version"] == ckpt_mod.FORMAT_VERSION
    assert info["mesh"] == {
        "n_shards": 8, "dcn_slices": 2, "host_order": [1, 0]}


# ------------------------------------------------- reshard bit-identity


@pytest.mark.slow  # ~17s double-reshard chain; tier-1 keeps the 1->8 reshard
# bit-identity pin which exercises the same v6 mesh-identity path
def test_reshard_8_to_4_to_1_bit_identical(tmp_path, straight):
    """A run checkpointed at 8 shards resumes at 4, checkpoints again,
    resumes unsharded, and finishes bit-identical to the uninterrupted
    single-device run — the full shrink chain a --retry wrapper walks
    when peers keep dying."""
    ck = str(tmp_path / "ck.npz")

    sim8 = _build(8)
    mid = sim8.run(4 * SECOND)
    save_checkpoint(ck, mid, meta={"sim_seconds": 4.0},
                    mesh_info=_mesh_info(sim8))
    assert read_header_info(ck)["mesh"]["n_shards"] == 8
    assert read_header_info(ck)["xchg_empty"] is True

    sim4 = _build(4)
    st4, meta = load_checkpoint(ck, sim4.state0, reshard=True)
    assert meta["sim_seconds"] == 4.0
    later = sim4.run(7 * SECOND, state=st4)
    save_checkpoint(ck, later, meta={"sim_seconds": 7.0},
                    mesh_info=_mesh_info(sim4))

    sim1 = _build(1)
    st1, _ = load_checkpoint(ck, sim1.state0, reshard=True)
    final = sim1.run(10 * SECOND, state=st1)

    _assert_portable_equal(_portable_leaves(final), straight, "8->4->1")


def test_reshard_1_to_8_bit_identical(tmp_path, straight):
    """The grow direction: an unsharded checkpoint restores onto an
    8-shard mesh (capacity came back) and still finishes bit-identical."""
    ck = str(tmp_path / "ck.npz")

    sim1 = _build(1)
    mid = sim1.run(4 * SECOND)
    save_checkpoint(ck, mid, meta={"sim_seconds": 4.0},
                    mesh_info=_mesh_info(sim1))
    assert read_header_info(ck)["mesh"]["n_shards"] == 1

    sim8 = _build(8)
    st8, _ = load_checkpoint(ck, sim8.state0, reshard=True)
    final = sim8.run(10 * SECOND, state=st8)

    _assert_portable_equal(_portable_leaves(final), straight, "1->8")


def test_reshard_refuses_inflight_exchange(tmp_path):
    """A checkpoint whose exchange buffer holds an in-flight event must
    refuse to restore onto a *different* mesh — dropping it silently
    would break the lossless contract."""
    ck = str(tmp_path / "ck.npz")
    sim8 = _build(8)
    save_checkpoint(ck, sim8.state0, mesh_info=_mesh_info(sim8))

    with np.load(ck, allow_pickle=False) as z:
        arrays = {k: z[k].copy() for k in z.files}
    header = json.loads(bytes(arrays["__header__"]).decode())
    idx = next(i for i, p in enumerate(header["paths"])
               if p.startswith(".xchg") and p.endswith(".time"))
    leaf = arrays[f"leaf_{idx}"]
    leaf.flat[0] = 0  # one occupied slot: an event in flight
    np.savez(ck, **arrays)

    sim4 = _build(4)
    with pytest.raises(ValueError, match="in-flight"):
        load_checkpoint(ck, sim4.state0, reshard=True)


def test_reshard_sharded_ckpt_onto_spill_template(tmp_path):
    """The CLI's unsharded default is `--overflow spill`, which sharded
    builds refuse — so every mesh->1 resume crosses spill *presence*.
    The empty ring starts fresh from the template, exactly like the
    exchange buffer (caught live: a `--test --mesh 2` run's checkpoint
    could not resume unsharded)."""
    ck = str(tmp_path / "ck.npz")
    sim2 = _build(2)
    save_checkpoint(ck, sim2.state0, mesh_info=_mesh_info(sim2))

    sim1 = build_simulation(parse_config(CONFIG), seed=7, overflow="spill")
    st, _ = load_checkpoint(ck, sim1.state0, reshard=True)

    def spill_leaves(state):
        return {p: np.asarray(jax.device_get(leaf)) for p, leaf in
                zip(_leaf_paths(state), jax.tree_util.tree_leaves(state))
                if p.startswith(".queues.spill")}

    got, tpl = spill_leaves(st), spill_leaves(sim1.state0)
    assert got and got.keys() == tpl.keys()
    for p in tpl:
        assert np.array_equal(got[p], tpl[p]), p
    _assert_portable_equal(
        {p: a for p, a in _portable_leaves(st).items()
         if not p.startswith(".queues.spill")},
        _portable_leaves(sim2.state0), "2->1+spill")


def test_reshard_spill_ckpt_onto_sharded_mesh(tmp_path):
    """1 -> S crosses spill presence the other way: an empty ring is
    dropped (it cannot exist on a mesh); a ring holding parked events
    refuses loudly — resharding must never lose a spilled event. Same
    shard count keeps loading the ring bit-exact (mid-pressure resume
    is 1->1 only, docs/13)."""
    ck = str(tmp_path / "ck.npz")
    sim1 = build_simulation(parse_config(CONFIG), seed=7, overflow="spill")
    save_checkpoint(ck, sim1.state0, mesh_info=_mesh_info(sim1))
    sim4 = _build(4)
    st, _ = load_checkpoint(ck, sim4.state0, reshard=True)
    assert not any(p.startswith(".queues.spill") for p in _leaf_paths(st))

    with np.load(ck, allow_pickle=False) as z:
        arrays = {k: z[k].copy() for k in z.files}
    header = json.loads(bytes(arrays["__header__"]).decode())
    idx = next(i for i, p in enumerate(header["paths"])
               if p.startswith(".queues.spill") and p.endswith(".wr"))
    arrays[f"leaf_{idx}"].flat[0] = 1  # one parked event
    header["crc32"][idx] = ckpt_mod._crc(arrays[f"leaf_{idx}"])
    arrays["__header__"] = np.frombuffer(
        json.dumps(header).encode(), dtype=np.uint8)
    np.savez(ck, **arrays)

    with pytest.raises(ValueError, match="spilled"):
        load_checkpoint(ck, sim4.state0, reshard=True)
    st11, _ = load_checkpoint(ck, sim1.state0, reshard=True)
    assert np.asarray(jax.device_get(st11.queues.spill.wr)).flat[0] == 1


def test_sharded_mesh_refuses_spill_modes():
    """The pressure reservoir's boundary protocol is single-device only;
    a sharded build must fail loudly at build time, not lose events."""
    with pytest.raises(ValueError, match="sharded"):
        build_simulation(parse_config(CONFIG), seed=7,
                         mesh=pmesh.make_mesh(2), overflow="spill")


# ---------------------------------------------------------- atomic IO


def test_atomic_write_retries_transient_enospc(tmp_path, monkeypatch):
    """A transient ENOSPC mid-write retries with exponential backoff and
    still lands a verifiable checkpoint (the partial tmp reclaimed)."""
    path = str(tmp_path / "ck.npz")
    fails = {"n": 2}
    real = ckpt_mod._savez
    sleeps = []

    def flaky(f, **arrs):
        if fails["n"] > 0:
            fails["n"] -= 1
            raise OSError(28, "No space left on device")  # ENOSPC
        real(f, **arrs)

    monkeypatch.setattr(ckpt_mod, "_savez", flaky)
    monkeypatch.setattr(ckpt_mod, "_io_sleep", sleeps.append)

    save_checkpoint(path, _tree(), meta={"ok": 1})
    assert verify_checkpoint(path)["ok"] == 1
    assert sleeps == [ckpt_mod._IO_BACKOFF_S, 2 * ckpt_mod._IO_BACKOFF_S]
    assert not os.path.exists(path + ".tmp")


def test_atomic_write_hard_failure_keeps_previous(tmp_path, monkeypatch):
    """When every attempt fails, the error propagates AND the previous
    good generation survives untouched — the crash the rename protocol
    exists for."""
    path = str(tmp_path / "ck.npz")
    save_checkpoint(path, _tree(), meta={"gen": 0})

    def always(f, **arrs):
        raise OSError(28, "No space left on device")

    monkeypatch.setattr(ckpt_mod, "_savez", always)
    monkeypatch.setattr(ckpt_mod, "_io_sleep", lambda s: None)
    with pytest.raises(OSError):
        save_checkpoint(path, _tree(), meta={"gen": 1})
    assert verify_checkpoint(path)["gen"] == 0
    assert not os.path.exists(path + ".tmp")

    # a non-transient errno fails fast, no retry loop
    calls = {"n": 0}

    def eacces(f, **arrs):
        calls["n"] += 1
        raise OSError(13, "Permission denied")

    monkeypatch.setattr(ckpt_mod, "_savez", eacces)
    with pytest.raises(OSError):
        save_checkpoint(str(tmp_path / "other.npz"), _tree())
    assert calls["n"] == 1


# ------------------------------------------------- resume candidates


def test_emergency_checkpoint_preferred(tmp_path):
    """The crash-path `.emergency` file outranks the bare generation on
    an mtime tie (it was written at death, so it is furthest along)."""
    path = str(tmp_path / "ck.npz")
    save_checkpoint(path, _tree(), meta={"which": "interval"})
    save_checkpoint(path + ".emergency", _tree(), meta={"which": "crash"})
    now = time.time()
    os.utime(path, (now, now))
    os.utime(path + ".emergency", (now, now))

    chosen, meta, skipped = find_resume_checkpoint(path)
    assert chosen == path + ".emergency"
    assert meta["which"] == "crash"
    assert skipped == []

    # a corrupt emergency file is skipped, falling back to the interval
    open(path + ".emergency", "wb").write(b"junk")
    chosen, meta, skipped = find_resume_checkpoint(path)
    assert chosen == path
    assert meta["which"] == "interval"
    assert [p for p, _ in skipped] == [path + ".emergency"]


def test_shard_set_is_all_or_none(tmp_path):
    """A complete sharded set resumes as a member list; a torn set is
    never chosen, only reported."""
    path = str(tmp_path / "ck.npz")
    tree = {"per_host": jnp.arange(8, dtype=jnp.int64).reshape(4, 2)}
    for i in range(2):
        save_checkpoint(path, {"per_host": tree["per_host"][2 * i:2 * i + 2]},
                        meta={"member": i}, shard=(i, 2))
    members = [shard_member_path(path, i, 2) for i in range(2)]
    assert all(os.path.exists(m) for m in members)

    chosen, meta, skipped = find_resume_checkpoint(path)
    assert chosen == members
    assert meta["member"] == 1  # meta of the last-verified member
    assert skipped == []

    from shadow_tpu.utils import load_shard_set

    state, meta0 = load_shard_set(members, tree)
    assert meta0["member"] == 0
    assert jnp.array_equal(state["per_host"], tree["per_host"])

    # tear the set: the survivor alone must NOT be offered for resume
    os.remove(members[1])
    with pytest.raises(ValueError, match="incomplete shard set"):
        find_resume_checkpoint(path)


# ----------------------------------------------------------- watchdog


def test_watchdog_peerlost_fires_with_bundle(tmp_path):
    from shadow_tpu.runtime import EXIT_PEER_LOST, Watchdog

    codes: list[int] = []
    wd = Watchdog(
        0.3, diag_dir=str(tmp_path), label="t", kind="peerlost",
        exit_code=EXIT_PEER_LOST,
        _exit=codes.append, _stream=open(os.devnull, "w"),
    )
    wd.pet(site="harvest.fetch", sim_seconds=3.0)
    wd.start()
    deadline = time.monotonic() + 10.0
    while not codes and time.monotonic() < deadline:
        time.sleep(0.05)
    wd.stop()
    assert codes == [EXIT_PEER_LOST]

    bundle_path = tmp_path / f"t.peerlost.{os.getpid()}.json"
    bundle = json.loads(bundle_path.read_text())
    assert bundle["exit_code"] == EXIT_PEER_LOST
    assert "peerlost deadline expired" in bundle["reason"]
    assert bundle["progress"]["site"] == "harvest.fetch"
    assert bundle["compile_graces"] == 0
    # the stack dump rides along, distinct from any .stall. bundle
    assert (tmp_path / f"t.peerlost.{os.getpid()}.stacks.txt").exists()


def test_watchdog_compile_grace_rearms_then_fires(tmp_path):
    """With compile_grace, a deadline expiry while the main thread shows
    jax compiler frames re-arms instead of firing; once the compile
    fiction ends, the next expiry fires for real and the bundle records
    how many graces were granted."""
    from shadow_tpu.runtime import EXIT_PEER_LOST, Watchdog

    codes: list[int] = []
    wd = Watchdog(
        0.2, diag_dir=str(tmp_path), label="g", kind="peerlost",
        exit_code=EXIT_PEER_LOST, compile_grace=True,
        _exit=codes.append, _stream=open(os.devnull, "w"),
    )
    answers = iter([True, True])
    wd._main_thread_compiling = lambda: next(answers, False)
    wd.start()
    deadline = time.monotonic() + 15.0
    while not codes and time.monotonic() < deadline:
        time.sleep(0.05)
    wd.stop()
    assert codes == [EXIT_PEER_LOST]
    assert wd.compile_graces == 2
    bundle = json.loads(
        (tmp_path / f"g.peerlost.{os.getpid()}.json").read_text())
    assert bundle["compile_graces"] == 2


def test_watchdog_without_compile_grace_ignores_compiler_frames(tmp_path):
    """compile_grace off (the classic per-window stall deadline): a
    compiling main thread does NOT extend the deadline."""
    from shadow_tpu.runtime import EXIT_STALL, Watchdog

    codes: list[int] = []
    wd = Watchdog(
        0.2, diag_dir=str(tmp_path), label="n",
        _exit=codes.append, _stream=open(os.devnull, "w"),
    )
    wd._main_thread_compiling = lambda: True
    wd.start()
    deadline = time.monotonic() + 10.0
    while not codes and time.monotonic() < deadline:
        time.sleep(0.05)
    wd.stop()
    assert codes == [EXIT_STALL]
    assert wd.compile_graces == 0


def test_main_thread_compiling_false_in_plain_code():
    from shadow_tpu.runtime import Watchdog

    wd = Watchdog(5.0, _exit=lambda c: None)
    assert wd._main_thread_compiling() is False  # we are not in jax lowering


# -------------------------------------------------------- retry loop


def test_exit_code_taxonomy():
    from shadow_tpu.runtime import (
        EXIT_INVARIANT,
        EXIT_PEER_LOST,
        EXIT_PRESSURE,
        EXIT_STALL,
        exit_retryable,
    )

    assert (EXIT_STALL, EXIT_INVARIANT, EXIT_PRESSURE, EXIT_PEER_LOST) \
        == (75, 70, 76, 77)
    assert exit_retryable(EXIT_STALL)
    assert exit_retryable(EXIT_PEER_LOST)
    assert exit_retryable(-int(signal.SIGKILL))  # Popen's signal death
    assert exit_retryable(128 + int(signal.SIGKILL))
    assert exit_retryable(128 + int(signal.SIGTERM))
    assert not exit_retryable(0)
    assert not exit_retryable(EXIT_INVARIANT)  # a bug, not a transient
    assert not exit_retryable(EXIT_PRESSURE)
    assert not exit_retryable(2)


def test_next_retry_argv_resume_and_shrink():
    from shadow_tpu.runtime import EXIT_PEER_LOST, EXIT_STALL, next_retry_argv

    # a stall relaunch resumes (from zero if no checkpoint yet) but
    # keeps its mesh: the peers are all still there
    assert next_retry_argv(["prog", "--mesh", "8"], EXIT_STALL) == \
        ["prog", "--mesh", "8", "--resume", "auto-if-any"]
    # an existing --resume is respected, not duplicated
    assert next_retry_argv(["prog", "--resume", "auto"], EXIT_STALL) == \
        ["prog", "--resume", "auto"]
    assert next_retry_argv(["prog", "--resume=auto"], EXIT_STALL) == \
        ["prog", "--resume=auto"]
    # peer lost: halve the mesh, both flag spellings, floor at 1
    assert next_retry_argv(["p", "--mesh", "8"], EXIT_PEER_LOST,
                           shrink=True)[:3] == ["p", "--mesh", "4"]
    assert next_retry_argv(["p", "--mesh=8"], EXIT_PEER_LOST,
                           shrink=True)[1] == "--mesh=4"
    assert next_retry_argv(["p", "--mesh", "1"], EXIT_PEER_LOST,
                           shrink=True)[:3] == ["p", "--mesh", "1"]


class _FakeProc:
    """Enough of Popen for run_with_retry: a scripted exit code and a
    pid that cannot exist, so the post-mortem killpg is a harmless
    ProcessLookupError."""

    def __init__(self, rc):
        self.rc = rc
        self.stderr = None
        self.pid = 2 ** 31 - 1

    def wait(self):
        return self.rc


def test_run_with_retry_recovers_and_shrinks():
    from shadow_tpu.runtime import run_with_retry

    rcs = iter([75, 77, 0])
    seen: list[list[str]] = []
    sleeps: list[float] = []

    def popen(argv, **kw):
        seen.append(list(argv))
        return _FakeProc(next(rcs))

    report = run_with_retry(["prog", "--mesh", "8"], retries=3,
                            backoff_s=0.5, _sleep=sleeps.append,
                            _popen=popen)
    assert report["attempts"] == 3
    assert report["recoveries"] == 2
    assert report["exit_code"] == 0
    assert report["exit_history"] == [75, 77, 0]
    assert len(report["mttr_s"]) == 2
    assert sleeps == [0.5, 1.0]  # exponential backoff
    assert seen[0] == ["prog", "--mesh", "8"]
    # stall: resume, same mesh
    assert seen[1] == ["prog", "--mesh", "8", "--resume", "auto-if-any"]
    # peer lost: resume AND halve
    assert seen[2] == ["prog", "--mesh", "4", "--resume", "auto-if-any"]


def test_run_with_retry_stops_on_nonretryable():
    from shadow_tpu.runtime import run_with_retry

    report = run_with_retry(["prog"], retries=5, _sleep=lambda s: None,
                            _popen=lambda argv, **kw: _FakeProc(2))
    assert report == {"attempts": 1, "recoveries": 0, "exit_code": 2,
                      "exit_history": [2], "mttr_s": []}


def test_run_with_retry_exhausts_budget():
    from shadow_tpu.runtime import run_with_retry

    report = run_with_retry(["prog"], retries=1, _sleep=lambda s: None,
                            _popen=lambda argv, **kw: _FakeProc(75))
    assert report["attempts"] == 2
    assert report["exit_code"] == 75
    assert report["exit_history"] == [75, 75]
    assert report["recoveries"] == 1


# ----------------------------------------------------------- zero cost


def test_elastic_host_order_plumbing_is_zero_cost():
    """`host_order` is the reshard-resume plumbing threaded through
    build_simulation; passing the identity permutation must leave the
    build indistinguishable — same leaves, same paths, byte-identical
    HLO. (The watchdog and retry loop live entirely outside the jitted
    program, so this pins the only build-path touch point.)"""
    from shadow_tpu.analysis.hlo_audit import assert_zero_cost

    cfg = parse_config(CONFIG)
    base = build_simulation(cfg, seed=7)
    off = build_simulation(cfg, seed=7,
                           host_order=list(range(len(base.names))))
    on = build_simulation(cfg, seed=7, trace=8)  # known-different build
    assert off.host_order is not None
    assert_zero_cost((base.engine, base.state0), (off.engine, off.state0),
                     (on.engine, on.state0), jnp.int64(base.stop_ns))


# ------------------------------------------------ chaos (subprocess)


def _cli_env(**extra):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["JAX_COMPILATION_CACHE_DIR"] = os.path.join(REPO, ".jax_cache_cpu")
    env["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = "0"
    env["JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES"] = "0"
    env.update(extra)
    return env


def _last_json(text):
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise AssertionError(f"no JSON summary line in output:\n{text}")


_CMP_KEYS = ("events", "windows", "net_dropped", "queue_drops",
             "fault_dropped", "quarantined_events", "sweeps",
             "rx_bytes", "tx_bytes", "events_by_kind")


def _sig(summary):
    return {k: summary[k] for k in _CMP_KEYS if k in summary}


@pytest.mark.slow
def test_collective_stall_exits_77_with_bundle(tmp_path):
    """Chaos acceptance, detection half: a wedged collective (injected
    via SHADOW_TPU_CHAOS_HANG_S) must trip the --collective-timeout
    deadline — exit 77 with a peerlost diagnostic bundle, not a hang."""
    cfg_path = tmp_path / "phold.config.xml"
    cfg_path.write_text(CONFIG)
    ck = str(tmp_path / "ck.npz")
    r = subprocess.run(
        [sys.executable, "-m", "shadow_tpu", str(cfg_path),
         "--seed", "1", "--mesh", "8", "--overflow", "drop",
         "--checkpoint-interval", "4", "--checkpoint-path", ck,
         "--collective-timeout", "3", "--diag-dir", str(tmp_path)],
        cwd=REPO, env=_cli_env(SHADOW_TPU_CHAOS_HANG_S="60"),
        capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 77, f"rc={r.returncode}\n{r.stderr}"
    bundles = glob.glob(str(tmp_path / "*.peerlost.*.json"))
    assert len(bundles) == 1, r.stderr
    bundle = json.loads(open(bundles[0]).read())
    assert bundle["exit_code"] == 77
    # the injection armed only after the first window, so the watchdog
    # had been petted with real progress before the wedge
    assert bundle["windows_reported"] > 0
    assert os.path.exists(ck + ".chaos")  # the one-shot marker


@pytest.mark.slow
def test_retry_recovers_from_wedged_collective_bit_identical(tmp_path):
    """Chaos acceptance, recovery half: the same wedged collective under
    --retry must come back on a halved mesh from the newest checkpoint
    and finish exit 0 with a summary bit-identical to a clean run."""
    cfg_path = tmp_path / "phold.config.xml"
    cfg_path.write_text(CONFIG)

    def run(tag, extra, **env):
        ck = str(tmp_path / f"{tag}.npz")
        r = subprocess.run(
            [sys.executable, "-m", "shadow_tpu", str(cfg_path),
             "--seed", "1", "--mesh", "8", "--overflow", "drop",
             "--checkpoint-interval", "4", "--checkpoint-path", ck,
             "--diag-dir", str(tmp_path)] + extra,
            cwd=REPO, env=_cli_env(**env),
            capture_output=True, text=True, timeout=600,
        )
        return r

    clean = run("clean", [])
    assert clean.returncode == 0, clean.stderr
    want = _sig(_last_json(clean.stdout))

    chaos = run(
        "chaos",
        ["--retry", "2", "--retry-backoff", "0.2",
         "--collective-timeout", "5"],
        SHADOW_TPU_CHAOS_HANG_S="60",
    )
    assert chaos.returncode == 0, chaos.stderr
    assert "retry report" in chaos.stderr
    report = json.loads(
        chaos.stderr.split("retry report ", 1)[1].splitlines()[0])
    assert 77 in report["exit_history"]
    assert report["exit_history"][-1] == 0
    assert report["recoveries"] >= 1
    assert report["mttr_s"], "MTTR must be measured per recovery"
    assert _sig(_last_json(chaos.stdout)) == want, (
        "recovered run diverged from the clean run")

"""pthreads for unmodified binaries: plugin threads on green threads.

The reference maps plugin pthreads onto its rpth cooperative scheduler
(/root/reference/src/external/rpth/pthread.c, exercised by
src/test/pthreads/test_pthreads.c). Here pthread_create spawns sibling
green threads inside the virtual process; mutex/cond state lives in the
caller's pthread_mutex_t/pthread_cond_t storage and blocking routes
through the runtime scheduler — so a thread holding a lock across a
blocking syscall parks its waiters instead of spinning the pump.
"""

import os
import shutil
import textwrap

import pytest

from shadow_tpu.config import parse_config

pytestmark = pytest.mark.skipif(
    shutil.which("gcc") is None, reason="no C toolchain"
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF_PTH = "/root/reference/src/test/pthreads/test_pthreads.c"

TOPO = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="packetloss" attr.type="double" for="edge" id="d4" />
  <key attr.name="latency" attr.type="double" for="edge" id="d3" />
  <key attr.name="bandwidthup" attr.type="int" for="node" id="d2" />
  <key attr.name="bandwidthdown" attr.type="int" for="node" id="d1" />
  <graph edgedefault="undirected">
    <node id="poi-1">
      <data key="d1">10240</data>
      <data key="d2">10240</data>
    </node>
    <edge source="poi-1" target="poi-1">
      <data key="d3">25.0</data>
      <data key="d4">0.0</data>
    </edge>
  </graph>
</graphml>"""


def one_host_config(plugin_path: str, plugin_id: str, args: str = "") -> str:
    return textwrap.dedent(f"""\
    <shadow stoptime="30">
      <topology><![CDATA[{TOPO}]]></topology>
      <plugin id="{plugin_id}" path="{plugin_path}"/>
      <host id="h0">
        <process plugin="{plugin_id}" starttime="1" arguments="{args}"/>
      </host>
    </shadow>""")


def test_reference_test_pthreads_unmodified(capfd):
    """Compile /root/reference/src/test/pthreads/test_pthreads.c
    UNMODIFIED and run it as a virtual process (VERDICT r03 item 5's
    required proof): joinable threads with heap retvals, 5-thread
    mutex-guarded sum, and trylock/cond_wait/broadcast coordination."""
    if not os.path.exists(REF_PTH):
        pytest.skip("reference tree not mounted")
    from shadow_tpu.proc import ProcessTier
    from shadow_tpu.proc.native import compile_posix_plugin

    plug = compile_posix_plugin(REF_PTH, name="ref_test_pthreads")
    cfg = parse_config(one_host_config(plug, "ref_test_pthreads"))
    tier = ProcessTier(cfg, seed=2)
    tier.run()
    out = capfd.readouterr().out
    assert tier.exit_codes == {0: 0}, (tier.exit_codes, out[-2000:])
    assert "pthreads test passed" in out
    tier.close()


def test_threads_block_independently(capfd):
    """A worker thread blocked in a pipe read must not stall its
    siblings: main sleeps in virtual time, then feeds the pipe; a second
    worker computes meanwhile. Exercises cross-thread fd sharing and
    per-thread scheduler blocking (the property rpth gives the
    reference's threaded plugins)."""
    from shadow_tpu.proc import ProcessTier
    from shadow_tpu.proc.native import compile_posix_plugin

    src = os.path.join(REPO, "native/plugins/_t_threads.c")
    with open(src, "w") as f:
        f.write(textwrap.dedent("""\
        #include <pthread.h>
        #include <stdio.h>
        #include <string.h>
        #include <unistd.h>

        static int pipefd[2];
        static int counted = 0;
        static pthread_mutex_t mux = PTHREAD_MUTEX_INITIALIZER;

        static void* reader(void* arg) {
            char buf[32] = {0};
            ssize_t n = read(pipefd[0], buf, sizeof buf); /* blocks */
            if (n <= 0 || strcmp(buf, "payload") != 0) return (void*)1;
            return (void*)0;
        }

        static void* counter(void* arg) {
            for (int i = 0; i < 1000; i++) {
                pthread_mutex_lock(&mux);
                counted++;
                pthread_mutex_unlock(&mux);
            }
            return (void*)0;
        }

        int main(void) {
            if (pipe(pipefd) != 0) return 10;
            pthread_t tr, tc;
            pthread_create(&tr, NULL, reader, NULL);
            pthread_create(&tc, NULL, counter, NULL);
            /* while the reader blocks, virtual time passes and the
             * counter finishes */
            usleep(500000);
            if (write(pipefd[1], "payload", 8) != 8) return 11;
            void *r1, *r2;
            pthread_join(tr, &r1);
            pthread_join(tc, &r2);
            if (r1 || r2 || counted != 1000) return 12;
            printf("THREADS_OK %d\\n", counted);
            return 0;
        }
        """))
    plug = compile_posix_plugin(src, name="_t_threads")
    cfg = parse_config(one_host_config(plug, "_t_threads"))
    tier = ProcessTier(cfg, seed=3)
    tier.run()
    out = capfd.readouterr().out
    assert tier.exit_codes == {0: 0}, (tier.exit_codes, out[-2000:])
    assert "THREADS_OK 1000" in out
    tier.close()
    os.remove(src)

"""Routing layer tests: GraphML parse, all-pairs paths, attachment, DNS.

Models the reference's path semantics checks (SURVEY.md §2.3): complete
graphs use direct edges, incomplete graphs use Dijkstra with multiplied
per-hop reliability, self paths double the min incident edge.
"""

import math

import jax.numpy as jnp
import numpy as np
import pytest

from shadow_tpu.core.timebase import MILLISECOND
from shadow_tpu.net.dns import DNS
from shadow_tpu.net.topology import Topology, Vertex

GRAPHML_1POI = """<?xml version="1.0" encoding="utf-8"?>
<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="packetloss" attr.type="double" for="edge" id="d6" />
  <key attr.name="latency" attr.type="double" for="edge" id="d5" />
  <key attr.name="packetloss" attr.type="double" for="node" id="d4" />
  <key attr.name="bandwidthdown" attr.type="int" for="node" id="d2" />
  <key attr.name="bandwidthup" attr.type="int" for="node" id="d1" />
  <graph edgedefault="undirected">
    <node id="poi-1">
      <data key="d1">2251</data><data key="d2">17038</data><data key="d4">0.0</data>
    </node>
    <edge source="poi-1" target="poi-1">
      <data key="d5">50.0</data><data key="d6">0.001</data>
    </edge>
  </graph>
</graphml>"""

# a 3-vertex line a - b - c (NOT complete): path a->c must go through b
GRAPHML_LINE = """<?xml version="1.0" encoding="utf-8"?>
<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="packetloss" attr.type="double" for="edge" id="d6" />
  <key attr.name="latency" attr.type="double" for="edge" id="d5" />
  <key attr.name="packetloss" attr.type="double" for="node" id="d4" />
  <key attr.name="bandwidthdown" attr.type="int" for="node" id="d2" />
  <key attr.name="bandwidthup" attr.type="int" for="node" id="d1" />
  <key attr.name="countrycode" attr.type="string" for="node" id="d3" />
  <key attr.name="type" attr.type="string" for="node" id="d7" />
  <graph edgedefault="undirected">
    <node id="a"><data key="d1">1000</data><data key="d2">1000</data>
      <data key="d4">0.01</data><data key="d3">US</data><data key="d7">client</data></node>
    <node id="b"><data key="d1">1000</data><data key="d2">1000</data>
      <data key="d4">0.0</data><data key="d3">US</data><data key="d7">relay</data></node>
    <node id="c"><data key="d1">1000</data><data key="d2">1000</data>
      <data key="d4">0.02</data><data key="d3">DE</data><data key="d7">client</data></node>
    <edge source="a" target="b"><data key="d5">10.0</data><data key="d6">0.1</data></edge>
    <edge source="b" target="c"><data key="d5">20.0</data><data key="d6">0.2</data></edge>
  </graph>
</graphml>"""


def test_single_poi_self_loop():
    top = Topology.from_graphml(GRAPHML_1POI)
    assert top.n_vertices == 1
    lat, rel, _jit = top.compute_all_pairs()
    # complete graph (self-loop present): direct edge used
    assert lat[0, 0] == pytest.approx(50.0)
    assert rel[0, 0] == pytest.approx(1 - 0.001, abs=1e-6)
    assert top.min_latency_ms == pytest.approx(50.0)


def test_line_graph_paths():
    top = Topology.from_graphml(GRAPHML_LINE)
    lat, rel, _jit = top.compute_all_pairs()
    a, b, c = 0, 1, 2
    # two-hop latency adds; reliability multiplies edge AND endpoint vertex terms
    assert lat[a, c] == pytest.approx(30.0)
    expect = (1 - 0.01) * (1 - 0.1) * (1 - 0.2) * (1 - 0.02)
    assert rel[a, c] == pytest.approx(expect, rel=1e-5)
    assert lat[a, b] == pytest.approx(10.0)
    assert rel[a, b] == pytest.approx((1 - 0.01) * (1 - 0.1), rel=1e-5)
    # self path: min incident edge twice, edge loss only (topology.c:1545-1652)
    assert lat[a, a] == pytest.approx(20.0)
    assert rel[a, a] == pytest.approx((1 - 0.1) ** 2, rel=1e-5)
    assert lat[b, b] == pytest.approx(20.0)


def test_attachment_hints():
    top = Topology.from_graphml(GRAPHML_LINE)
    # country+type beats country alone
    assert top.attach(countrycode_hint="US", type_hint="relay") == 1
    assert top.attach(countrycode_hint="DE") == 2
    # round-robin across the US class
    seen = {top.attach(countrycode_hint="US") for _ in range(4)}
    assert seen == {0, 1}
    # unmatchable hints fall back to the all-class
    v = top.attach(countrycode_hint="XX")
    assert v in (0, 1, 2)


def test_device_network_route():
    top = Topology.from_graphml(GRAPHML_LINE)
    # hosts: h0@a h1@a h2@c
    net = top.build_network([0, 0, 2])
    lat, rel, _jit = net.route(jnp.asarray([0, 0, 1]), jnp.asarray([2, 1, 0]))
    assert int(lat[0]) == 30 * MILLISECOND
    # h0 -> h1 both attach to vertex a: self path = 2 * 10ms
    assert int(lat[1]) == 20 * MILLISECOND
    assert int(lat[2]) == 20 * MILLISECOND
    assert net.min_latency_ns == 10 * MILLISECOND


def test_pointer_jump_matches_bruteforce():
    """Random graphs: pointer-jumped path reliability == per-pair walk."""
    rng = np.random.default_rng(7)
    for trial in range(3):
        v = 12
        verts = [Vertex(vid=str(i), index=i) for i in range(v)]
        edges = []
        for i in range(v):
            for j in range(i + 1, v):
                if rng.random() < 0.35:
                    edges.append(
                        (i, j, float(rng.integers(1, 50)), float(rng.random() * 0.3), 0.0)
                    )
        # ensure connectivity via a ring
        for i in range(v):
            edges.append((i, (i + 1) % v, 60.0, 0.05, 0.0))
        top = Topology(verts, edges)
        lat, rel, _jit = top.compute_all_pairs()

        import networkx as nx

        g = nx.Graph()
        g.add_nodes_from(range(v))
        for u, w, l, loss, _ in edges:
            if not g.has_edge(u, w) or g[u][w]["lat"] > l:
                g.add_edge(u, w, lat=l, loss=loss)
        for s in range(v):
            lengths, paths = nx.single_source_dijkstra(g, s, weight="lat")
            for d in range(v):
                if d == s:
                    continue
                assert lat[s, d] == pytest.approx(lengths[d]), (s, d)
                p = paths[d]
                r = 1.0
                for x, y in zip(p[:-1], p[1:]):
                    r *= 1 - g[x][y]["loss"]
                assert rel[s, d] == pytest.approx(r, rel=1e-4), (s, d)


def test_reference_topology_loads():
    """The shipped measured Internet topology parses and yields tables."""
    import os

    path = "/root/reference/resource/topology.graphml.xml.xz"
    if not os.path.exists(path):
        pytest.skip("reference topology not present")
    top = Topology.from_graphml(path)
    assert top.n_vertices > 10
    lat, rel, _jit = top.compute_all_pairs()
    assert np.isfinite(lat).all()
    assert (rel > 0).all() and (rel <= 1).all()
    # symmetric undirected measured graph -> symmetric latency
    assert np.allclose(lat, lat.T)


def test_dns():
    dns = DNS()
    a = dns.register(0, "alpha")
    b = dns.register(1, "beta", requested_ip="11.0.0.50")
    c = dns.register(2, "gamma", requested_ip="127.0.0.1")  # reserved -> auto
    assert a.ip_str == "1.0.0.0"  # first counter value past the 0.0.0.0/8 block
    assert b.ip_str == "11.0.0.50"
    assert c.ip_str != "127.0.0.1"
    assert dns.resolve_name("beta").host_id == 1
    assert dns.resolve_ip("11.0.0.50").name == "beta"
    assert dns.address_of(2) is c
    with pytest.raises(ValueError):
        dns.register(3, "alpha")

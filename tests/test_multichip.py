"""Sharded-engine tests on the 8-device virtual CPU mesh.

Validates the TPU build's core scale-out claim (SURVEY.md §2.4, §7 step 8):
hosts block-partitioned over a mesh axis, cross-shard packet delivery via
collectives, pmin window barrier — and bit-identical results vs. the
single-shard engine (the determinism contract must survive sharding).
"""

import jax
import jax.numpy as jnp

from shadow_tpu.core.timebase import SECOND
from shadow_tpu.models import phold
from shadow_tpu.parallel import mesh as pmesh


def test_sharded_phold_runs_and_matches_single():
    n_shards = 4
    per = 8
    n_hosts = n_shards * per
    stop = 1 * SECOND

    # single-shard reference run
    eng1, init1 = phold.build(n_hosts, seed=3, capacity=32)
    st1 = jax.jit(eng1.run)(init1(), stop)

    # sharded run over 4 virtual devices
    engN, initN = phold.build(
        per, seed=3, capacity=32, axis_name=pmesh.HOSTS_AXIS, n_shards=n_shards
    )
    m = pmesh.make_mesh(n_shards)
    init, run, _ = pmesh.build_sharded(engN, initN, m, per)
    stN = run(init(), jnp.int64(stop))

    assert int(stN.now) == stop
    # identical per-host trajectories regardless of sharding
    assert st1.hosts.n_received.tolist() == stN.hosts.n_received.tolist()
    assert st1.stats.n_executed.tolist() == stN.stats.n_executed.tolist()
    assert st1.src_seq.tolist() == stN.src_seq.tolist()
    # queue contents equal as multisets per host (slot order may differ)
    assert (st1.queues.time.sort(axis=1) == stN.queues.time.sort(axis=1)).all()


def test_sharded_step_window_advances():
    n_shards, per = 8, 4
    engN, initN = phold.build(
        per, seed=1, capacity=16, axis_name=pmesh.HOSTS_AXIS, n_shards=n_shards
    )
    m = pmesh.make_mesh(n_shards)
    init, _, step = pmesh.build_sharded(engN, initN, m, per)
    st = init()
    st2 = step(st, jnp.int64(SECOND))
    assert int(st2.now) > int(st.now)
    assert int(st2.stats.n_executed.sum()) > 0

"""Sharded-engine tests on the 8-device virtual CPU mesh.

Validates the TPU build's core scale-out claim (SURVEY.md §2.4, §7 step 8):
hosts block-partitioned over a mesh axis, cross-shard packet delivery via
collectives, pmin window barrier — and bit-identical results vs. the
single-shard engine (the determinism contract must survive sharding).
"""

import jax
import jax.numpy as jnp

from shadow_tpu.core.timebase import SECOND
from shadow_tpu.models import phold
from shadow_tpu.parallel import mesh as pmesh


def test_sharded_phold_runs_and_matches_single():
    n_shards = 4
    per = 8
    n_hosts = n_shards * per
    stop = 1 * SECOND

    # single-shard reference run
    eng1, init1 = phold.build(n_hosts, seed=3, capacity=32)
    st1 = jax.jit(eng1.run)(init1(), stop)

    # sharded run over 4 virtual devices
    engN, initN = phold.build(
        per, seed=3, capacity=32, axis_name=pmesh.HOSTS_AXIS, n_shards=n_shards
    )
    m = pmesh.make_mesh(n_shards)
    init, run, _ = pmesh.build_sharded(engN, initN, m, per)
    stN = run(init(), jnp.int64(stop))

    assert int(stN.now) == stop
    # identical per-host trajectories regardless of sharding
    assert st1.hosts.n_received.tolist() == stN.hosts.n_received.tolist()
    assert st1.stats.n_executed.tolist() == stN.stats.n_executed.tolist()
    assert st1.src_seq.tolist() == stN.src_seq.tolist()
    # queue contents equal as multisets per host (slot order may differ)
    assert (st1.queues.time.sort(axis=1) == stN.queues.time.sort(axis=1)).all()


TOPO_1POI = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="packetloss" attr.type="double" for="edge" id="d4" />
  <key attr.name="latency" attr.type="double" for="edge" id="d3" />
  <key attr.name="bandwidthup" attr.type="int" for="node" id="d2" />
  <key attr.name="bandwidthdown" attr.type="int" for="node" id="d1" />
  <graph edgedefault="undirected">
    <node id="poi-1">
      <data key="d1">10240</data>
      <data key="d2">10240</data>
    </node>
    <edge source="poi-1" target="poi-1">
      <data key="d3">25.0</data>
      <data key="d4">0.0</data>
    </edge>
  </graph>
</graphml>"""


def _tgen_pair_config(n_pairs: int) -> str:
    """n_pairs TGen client/server pairs (2*n_pairs hosts) on one PoI."""
    hosts = []
    for i in range(n_pairs):
        hosts.append(
            f'<host id="server{i}">'
            f'<process plugin="tgen" starttime="1" '
            f'arguments="server port=8888"/></host>'
        )
        hosts.append(
            f'<host id="client{i}">'
            f'<process plugin="tgen" starttime="2" '
            f'arguments="peers=server{i}:8888 sendsize=4KiB recvsize=8KiB '
            f'count=2 pause=1"/></host>'
        )
    return (
        '<shadow stoptime="30">'
        f"<topology><![CDATA[{TOPO_1POI}]]></topology>"
        '<plugin id="tgen" path="~/.shadow/bin/tgen"/>' + "".join(hosts)
        + "</shadow>"
    )


def test_sharded_tgen_tcp_matches_single():
    """The full config-driven TCP/TGen stack, sharded over 4 shards, must
    be bit-identical to the single-shard run (VERDICT round 1 item 2:
    sharding the *real* stack, not just raw PHOLD)."""
    from shadow_tpu.config import parse_config
    from shadow_tpu.sim import build_simulation

    cfg = parse_config(_tgen_pair_config(4))  # 8 hosts

    sim1 = build_simulation(cfg, seed=7)
    st1 = sim1.run()

    simN = build_simulation(cfg, seed=7, mesh=pmesh.make_mesh(4))
    stN = simN.run()

    assert int(stN.now) == int(st1.now)
    a1, aN = st1.hosts.app, stN.hosts.app
    assert a1.streams_done.tolist() == aN.streams_done.tolist()
    assert a1.conn_rx.tolist() == aN.conn_rx.tolist()
    assert a1.t_last_done.tolist() == aN.t_last_done.tolist()
    s1, sN = st1.hosts.net.sockets, stN.hosts.net.sockets
    assert s1.rx_bytes.sum(1).tolist() == sN.rx_bytes.sum(1).tolist()
    assert s1.tx_bytes.sum(1).tolist() == sN.tx_bytes.sum(1).tolist()
    assert st1.stats.n_executed.tolist() == stN.stats.n_executed.tolist()
    # streams actually completed (the workload exercised TCP end to end)
    assert int(a1.streams_done.sum()) > 0


def test_sharded_step_window_advances():
    n_shards, per = 8, 4
    engN, initN = phold.build(
        per, seed=1, capacity=16, axis_name=pmesh.HOSTS_AXIS, n_shards=n_shards
    )
    m = pmesh.make_mesh(n_shards)
    init, _, step = pmesh.build_sharded(engN, initN, m, per)
    st = init()
    st2 = step(st, jnp.int64(SECOND))
    assert int(st2.now) > int(st.now)
    assert int(st2.stats.n_executed.sum()) > 0


def test_multislice_2d_mesh_matches_single():
    """Multi-slice: a 2x4 ("dcn" x "hosts") mesh — the reference's
    unfinished multi-machine design (master.c:414-416) — must be
    bit-identical to the single-device run for the full TCP/TGen stack,
    with collectives over the combined axis tuple."""
    from shadow_tpu.config import parse_config
    from shadow_tpu.sim import build_simulation

    cfg = parse_config(_tgen_pair_config(4))  # 8 hosts

    sim1 = build_simulation(cfg, seed=7)
    st1 = sim1.run()

    m2 = pmesh.make_mesh(8, dcn_slices=2)
    assert m2.axis_names == (pmesh.DCN_AXIS, pmesh.HOSTS_AXIS)
    simN = build_simulation(cfg, seed=7, mesh=m2)
    stN = simN.run()

    assert int(stN.now) == int(st1.now)
    a1, aN = st1.hosts.app, stN.hosts.app
    assert a1.streams_done.tolist() == aN.streams_done.tolist()
    assert st1.stats.n_executed.tolist() == stN.stats.n_executed.tolist()
    s1, sN = st1.hosts.net.sockets, stN.hosts.net.sockets
    assert s1.rx_bytes.sum(1).tolist() == sN.rx_bytes.sum(1).tolist()
    assert int(a1.streams_done.sum()) > 0

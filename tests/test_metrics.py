"""Live telemetry plane (shadow_tpu/obs/metrics.py + server.py): the
metrics registry, OpenMetrics exporter, flight recorder, health state
machine, and their CLI wiring (docs/14-Telemetry.md).

The contracts under test mirror the measure_all.sh metrics_smoke gates:
the exporter is deterministic between ingests, syntactically valid
OpenMetrics, and reconciles exactly with the tracker's [metrics]
heartbeat rows and the end-of-run summary — single-shard and on the
forced 8-device mesh. With --metrics off, the harvest extraction must
lower byte-identically (the zero-cost pin, via the shared auditor
helper). Forced pressure exits must ship the flight-recorder ring in
their diagnostic bundle.
"""

import glob
import io
import json
import textwrap
import urllib.error
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest

from shadow_tpu.config import parse_config
from shadow_tpu.obs.metrics import (
    METRICS_HEADER,
    SPECS,
    FlightRecorder,
    HealthState,
    MetricsRegistry,
    validate_openmetrics,
)
from shadow_tpu.obs.server import MetricsServer
from shadow_tpu.sim import build_simulation
from shadow_tpu.tools.parse_shadow import parse_lines

TOPO = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="latency" attr.type="double" for="edge" id="d3" />
  <key attr.name="bandwidthup" attr.type="int" for="node" id="d2" />
  <key attr.name="bandwidthdown" attr.type="int" for="node" id="d1" />
  <graph edgedefault="undirected">
    <node id="poi-1">
      <data key="d1">2048</data>
      <data key="d2">2048</data>
    </node>
    <edge source="poi-1" target="poi-1">
      <data key="d3">50.0</data>
    </edge>
  </graph>
</graphml>"""

# 16 PHOLD hosts through one 50ms self-edge: small enough to run in
# seconds on the CPU backend, busy enough that an 8-shard mesh carries
# cross-shard traffic every window (the chaos-smoke shape)
PHOLD_CFG = textwrap.dedent(f"""\
<shadow stoptime="6">
  <topology><![CDATA[{TOPO}]]></topology>
  <plugin id="phold" path="shadow-plugin-test-phold.so" />
  <host id="peer" quantity="16">
    <process plugin="phold" starttime="1"
      arguments="basename=peer quantity=16 load=4" />
  </host>
</shadow>
""")


# ------------------------------------------------------------- registry


def test_specs_are_a_complete_catalog():
    names = [s.name for s in SPECS]
    assert len(names) == len(set(names))
    for s in SPECS:
        assert s.name.startswith("shadow_tpu_")
        assert s.kind in ("counter", "gauge")
        assert s.help and s.source  # provenance is part of the contract


def test_registry_ingest_is_cumulative_not_additive():
    reg = MetricsRegistry(version="1.2.3", n_shards=4)
    reg.ingest({"now_ns": 5_000_000_000, "executed": 10, "windows": 2,
                "sweeps": 3, "queue_drops": 1},
               extras={"rx_bytes": 100, "tx_bytes": 90, "net_dropped": 0,
                       "fault_dropped": 0, "quarantined": 0,
                       "cross_shard": 7},
               fill=0.5)
    reg.ingest({"now_ns": 10_000_000_000, "executed": 25},
               extras={"rx_bytes": 250}, fill=0.25)
    t = reg.totals()
    # harvest counters are already cumulative device sums: the second
    # ingest REPLACES, it must not add (25, not 35)
    assert t["shadow_tpu_events"] == 25
    assert t["shadow_tpu_rx_bytes"] == 250
    assert t["shadow_tpu_cross_shard_packets"] == 7
    assert t["shadow_tpu_sim_seconds"] == 10
    assert t["shadow_tpu_queue_fill"] == 0.25
    assert t["shadow_tpu_heartbeats"] == 2
    assert t["shadow_tpu_shards"] == 4


def test_registry_finalize_aligns_with_summary():
    reg = MetricsRegistry()
    reg.ingest({"executed": 10, "now_ns": 1_000_000_000})
    reg.finalize({"events": 42, "windows": 6, "rx_bytes": 1024,
                  "sim_seconds": 9.0,
                  "pressure": {"spilled": 5, "refilled": 5, "resident": 0}})
    t = reg.totals()
    assert t["shadow_tpu_events"] == 42
    assert t["shadow_tpu_windows"] == 6
    assert t["shadow_tpu_rx_bytes"] == 1024
    assert t["shadow_tpu_sim_seconds"] == 9
    assert t["shadow_tpu_spilled"] == 5
    assert t["shadow_tpu_pressure_refills"] == 5


def test_metrics_row_matches_header_shape():
    cols = METRICS_HEADER.rsplit("] ", 1)[1].split(",")
    reg = MetricsRegistry()
    reg.ingest({"executed": 7}, extras={"rx_bytes": 64, "tx_bytes": 64},
               fill=0.125)
    row = reg.metrics_row(30)
    parts = row.split(",")
    assert len(parts) == len(cols)
    assert parts[0] == "30"
    assert parts[cols.index("events")] == "7"
    assert parts[cols.index("rx-bytes")] == "64"
    assert float(parts[cols.index("queue-fill")]) == 0.125
    # integers render bare so the CSV reconciles with int() parsing
    assert "." not in parts[cols.index("events")]


def test_observe_folds_host_side_sources():
    class _Prof:
        def summary(self):
            return {"phases": {"drain": {"count": 4, "total_s": 0.5},
                               "pump": {"count": 4, "total_s": 0.25}}}

    reg = MetricsRegistry()
    h = HealthState()
    h.pressure_event()
    reg.observe(watchdog_margin_s=12.5, checkpoints=3, health=h,
                profiler=_Prof())
    t = reg.totals()
    assert t["shadow_tpu_watchdog_margin_seconds"] == 12.5
    assert t["shadow_tpu_checkpoints"] == 3
    assert t["shadow_tpu_health"] == 1
    assert t["shadow_tpu_phase_seconds{phase=drain}"] == 0.5
    text = reg.render()
    assert 'shadow_tpu_phase_seconds_total{phase="drain"} 0.5' in text
    assert 'shadow_tpu_phase_calls_total{phase="pump"} 4' in text


# ------------------------------------------------------------- exporter


def test_render_is_deterministic_and_valid():
    reg = MetricsRegistry(version="0.1.0")
    reg.ingest({"executed": 123, "now_ns": 2_500_000_000},
               extras={"rx_bytes": 8192}, fill=0.75)
    a, b = reg.render(), reg.render()
    assert a == b  # no scrape-varying state in the exposition
    assert validate_openmetrics(a) == []
    assert a.endswith("# EOF\n")
    assert "shadow_tpu_events_total 123" in a
    assert 'shadow_tpu_build_info{version="0.1.0"} 1' in a
    # every declared family renders its TYPE/HELP pair
    for s in SPECS:
        assert f"# TYPE {s.name} {s.kind}" in a
        assert f"# HELP {s.name} " in a


def test_validate_openmetrics_catches_malformations():
    assert validate_openmetrics("shadow_tpu_x 1\n")  # no TYPE, no EOF
    bad_counter = ("# TYPE f counter\n# HELP f h\nf 1\n# EOF\n")
    assert any("_total" in e for e in validate_openmetrics(bad_counter))
    bad_gauge = ("# TYPE g gauge\n# HELP g h\ng_total 1\n# EOF\n")
    assert any("must not" in e for e in validate_openmetrics(bad_gauge))
    dup = ("# TYPE f counter\n# HELP f h\nf_total 1\nf_total 2\n# EOF\n")
    assert any("duplicate" in e for e in validate_openmetrics(dup))
    no_eof = "# TYPE g gauge\n# HELP g h\ng 1\n"
    assert any("EOF" in e for e in validate_openmetrics(no_eof))
    ok = "# TYPE g gauge\n# HELP g h\ng{a=\"b\"} 1.5\n# EOF\n"
    assert validate_openmetrics(ok) == []


# --------------------------------------------------------------- health


def test_health_state_machine():
    h = HealthState()
    assert h.code() == 0 and h.http_status() == 200
    assert h.snapshot() == {"status": "ok", "causes": [],
                            "exit_code": None}
    # a comfortable margin is not a near-miss
    assert h.observe_margin(9.0, timeout_s=10.0) is False
    assert h.code() == 0
    # under NEAR_MISS_FRAC of the deadline degrades (sticky) — still 200
    assert h.observe_margin(2.0, timeout_s=10.0) is True
    assert h.code() == 1 and h.http_status() == 200
    h.pressure_event()
    h.relaunch(2)
    snap = h.snapshot()
    assert snap["status"] == "degraded"
    assert snap["causes"] == ["watchdog-near-miss", "pressure",
                              "retry-relaunch-2"]
    # an abnormal exit code chosen -> failed, 503
    h.fail(76)
    assert h.code() == 2 and h.http_status() == 503
    assert h.snapshot()["exit_code"] == 76


def test_health_no_watchdog_never_degrades():
    h = HealthState()
    assert h.observe_margin(0.0, timeout_s=0.0) is False
    assert h.code() == 0


# ------------------------------------------------------ flight recorder


def test_flight_recorder_is_a_bounded_json_ring():
    fr = FlightRecorder(capacity=4)
    for i in range(10):
        fr.record_heartbeat(i * 1_000_000_000,
                            {"executed": np.int64(i * 5), "windows": i,
                             "profile": {"dropped": "nested"}})
    fr.record_event("checkpoint", sim_seconds=3.0, path=object())
    snap = fr.snapshot()
    assert snap["capacity"] == 4
    assert len(snap["heartbeats"]) == 4  # ring keeps only the last K
    assert snap["heartbeats"][-1]["executed"] == 45
    assert snap["heartbeats"][-1]["sim_seconds"] == 9.0
    assert "profile" not in snap["heartbeats"][-1]
    assert snap["events"][0]["kind"] == "checkpoint"
    assert "path" not in snap["events"][0]  # non-scalars are dropped
    json.dumps(snap)  # numpy scalars were converted: bundle-safe


# ---------------------------------------------------------- HTTP server


def _get(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10) as r:
            return r.status, r.read().decode(), r.headers.get_content_type()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode(), e.headers.get_content_type()


def test_server_endpoints():
    reg = MetricsRegistry(version="0.1.0")
    reg.ingest({"executed": 11, "now_ns": 1_000_000_000})
    health = HealthState()
    fr = FlightRecorder()
    fr.record_event("xprof-start", sim_seconds=1.0)
    stream = io.StringIO()
    srv = MetricsServer(reg, health, fr, port=0, _stream=stream).start()
    try:
        assert f":{srv.port}/metrics" in stream.getvalue()
        st, a, ct = _get(srv.port, "/metrics")
        _, b, _ = _get(srv.port, "/metrics")
        assert st == 200 and a == b  # scrape determinism over HTTP
        assert ct == "application/openmetrics-text"
        assert validate_openmetrics(a) == []
        assert "shadow_tpu_events_total 11" in a

        st, body, ct = _get(srv.port, "/healthz")
        assert st == 200 and ct == "application/json"
        assert json.loads(body)["status"] == "ok"

        st, body, _ = _get(srv.port, "/summary.json")
        s = json.loads(body)
        assert st == 200
        assert s["totals"]["shadow_tpu_events"] == 11
        assert s["health"]["status"] == "ok"
        assert s["flight_recorder"]["events"] == 1
        assert s["scrapes"]["metrics"] == 2

        assert _get(srv.port, "/nope")[0] == 404

        # exit-code-aware: a failure flips /healthz to 503; /metrics
        # keeps serving the final counters for the post-mortem scrape
        health.fail(70)
        st, body, _ = _get(srv.port, "/healthz")
        assert st == 503 and json.loads(body)["exit_code"] == 70
        assert _get(srv.port, "/metrics")[0] == 200
    finally:
        srv.close()
    # closed: the port no longer answers
    with pytest.raises((urllib.error.URLError, ConnectionError, OSError)):
        urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=2)


# ------------------------------------------------------------- zero cost


def test_metrics_off_is_zero_cost():
    """With --metrics off, the harvest extraction lowers byte-identically
    to a build that never heard of the telemetry plane; on, it gains the
    extras reductions (non-vacuity). Checked through the shared auditor
    helper on the real extraction jits."""
    from shadow_tpu.analysis.hlo_audit import assert_zero_cost
    from shadow_tpu.runtime.harvest import HeartbeatHarvest

    cfg = parse_config(PHOLD_CFG)
    sim_b = build_simulation(cfg, seed=3)
    sim_off = build_simulation(cfg, seed=3)
    sim_on = build_simulation(cfg, seed=3)

    def extract_fn(sim, metrics):
        h = HeartbeatHarvest(sim, metrics=metrics)
        f = h._build(True)
        return lambda st, stop, f=f: f(st)  # auditor passes (state, stop)

    assert_zero_cost(
        (extract_fn(sim_b, None), sim_b.state0),
        (extract_fn(sim_off, None), sim_off.state0),
        (extract_fn(sim_on, MetricsRegistry()), sim_on.state0),
        jnp.int64(0),
    )


# ------------------------------------------------------------ CLI wiring


def _run_cli(capsys, argv):
    from shadow_tpu.cli import main

    rc = main(argv)
    out = capsys.readouterr().out
    summary = {}
    for line in reversed(out.strip().splitlines()):
        if line.startswith("{"):
            summary = json.loads(line)
            break
    return rc, out, summary


@pytest.mark.slow  # ~14s CLI run; the in-process registry/OpenMetrics
# reconciliation pins stay in tier-1
def test_cli_metrics_rows_reconcile_with_summary(capsys):
    rc, out, summary = _run_cli(capsys, [
        "--test", "--stoptime", "8", "--heartbeat-frequency", "4",
        "--metrics",
    ])
    assert rc == 0
    assert METRICS_HEADER in out
    met = parse_lines(out.splitlines())["metrics"]
    assert len(met["ticks"]) >= 2
    assert met["heartbeats"] == sorted(met["heartbeats"])  # monotone
    # the last [metrics] row IS the registry the exporter serves; it
    # must equal the end-of-run summary exactly
    for key in ("events", "queue_drops", "net_dropped", "fault_dropped",
                "cross_shard_packets", "rx_bytes", "tx_bytes"):
        assert met[key][-1] == int(summary[key]), key
    assert summary["rx_bytes"] > 0


def test_cli_without_metrics_emits_no_metrics_section(capsys):
    rc, out, _ = _run_cli(capsys, [
        "--test", "--stoptime", "4", "--heartbeat-frequency", "2",
    ])
    assert rc == 0
    assert "[metrics" not in out


def test_sharded_metrics_reconcile_with_single_shard(tmp_path, capsys):
    """The acceptance reconciliation on a forced multi-shard mesh: the
    registry's totals on --mesh 8 equal the single-device run's — every
    exported reduction is a global sum, so sharding must not change a
    single counter (cross_shard_packets excepted: it measures the mesh
    itself)."""
    cfg = tmp_path / "phold.xml"
    cfg.write_text(PHOLD_CFG)
    rc1, out1, sum1 = _run_cli(capsys, [
        str(cfg), "--metrics", "--heartbeat-frequency", "3",
        "--overflow", "drop", "--seed", "1",
    ])
    rc8, out8, sum8 = _run_cli(capsys, [
        str(cfg), "--metrics", "--heartbeat-frequency", "3",
        "--overflow", "drop", "--seed", "1", "--mesh", "8",
    ])
    assert rc1 == 0 and rc8 == 0
    for key in ("events", "windows", "queue_drops", "net_dropped",
                "fault_dropped", "rx_bytes", "tx_bytes"):
        assert int(sum1[key]) == int(sum8[key]), key
    m1 = parse_lines(out1.splitlines())["metrics"]
    m8 = parse_lines(out8.splitlines())["metrics"]
    assert m1["ticks"] and m8["ticks"]
    # exporter-vs-exporter: the final cumulative rows agree too
    for key in ("events", "queue_drops", "rx_bytes", "tx_bytes"):
        assert m1[key][-1] == m8[key][-1] == int(sum1[key]), key
    assert sum8["cross_shard_packets"] > 0  # the mesh actually exchanged


def test_exit76_bundle_ships_flight_recorder(tmp_path):
    from shadow_tpu.cli import main
    from shadow_tpu.runtime import EXIT_PRESSURE

    rc = main([
        "--test", "--stoptime", "4", "--capacity", "4",
        "--overflow", "strict", "--heartbeat-frequency", "0.2",
        "--diag-dir", str(tmp_path),
    ])
    assert rc == EXIT_PRESSURE == 76
    bundles = glob.glob(str(tmp_path / "*.pressure.*.json"))
    assert len(bundles) == 1
    with open(bundles[0]) as f:
        b = json.load(f)
    fr = b["flight_recorder"]
    # the black box ships its own recent history: at least the last 8
    # heartbeat summaries leading into the trip
    assert len(fr["heartbeats"]) >= 8
    sims = [hb["sim_seconds"] for hb in fr["heartbeats"]]
    assert sims == sorted(sims)
    assert all("executed" in hb for hb in fr["heartbeats"])


def test_xprof_flag_validation():
    from shadow_tpu.cli import main

    assert main(["--test", "--stoptime", "1", "--xprof", "nonsense"]) == 2
    assert main(["--test", "--stoptime", "1", "--xprof", "5:2"]) == 2
    assert main(["--test", "--stoptime", "1", "--xprof", "3:3"]) == 2


# ------------------------------------------------------- parser & plots


def test_parse_lines_tolerates_interleaved_sections():
    lines = [
        "x [shadow-heartbeat] [metrics] 20,50,0,0,0,0,900,900,0.5,2",
        "x [shadow-heartbeat] [node] 20,a,0,0,0,0,0,0,0,0,0,30,0,0",
        "x [shadow-heartbeat] [supervisor] 20,4,1.0,10.0,,1",
        # an earlier tick arriving later (resumed / concatenated logs)
        "x [shadow-heartbeat] [metrics] 10,20,0,0,0,0,400,400,0.25,1",
        "x [shadow-heartbeat] [node] 10,a,0,0,0,0,0,0,0,0,0,20,0,0",
        "x [shadow-heartbeat] [supervisor] 10,2,1.0,10.0,,0",
    ]
    stats = parse_lines(lines)
    assert stats["metrics"]["ticks"] == [10, 20]
    assert stats["metrics"]["events"] == [20, 50]
    assert stats["metrics"]["queue_fill"] == [0.25, 0.5]
    assert stats["metrics"]["heartbeats"] == [1, 2]
    assert stats["nodes"]["a"]["ticks"] == [10, 20]
    assert stats["nodes"]["a"]["events_executed"] == [20, 30]
    assert stats["supervisor"]["ticks"] == [10, 20]
    assert stats["supervisor"]["checkpoints_written"] == [0, 1]


def test_plot_shadow_metrics_figure_is_conditional(tmp_path):
    from shadow_tpu.tools.plot_shadow import make_figures

    node = {"ticks": [10, 20], "events_executed": [20, 30],
            **{f: [0, 0] for f in (
                "bytes_payload_recv", "bytes_payload_send",
                "bytes_wire_recv", "bytes_wire_send",
                "packets_recv", "packets_send",
                "bytes_header_recv", "bytes_header_send",
                "retrans_segments", "queue_drops", "tail_drops")}}
    base = {"nodes": {"a": node}}
    assert len(make_figures(dict(base), str(tmp_path), "png")) == 4
    with_metrics = dict(base)
    with_metrics["metrics"] = {
        "ticks": [10, 20], "events": [20, 50], "queue_drops": [0, 0],
        "net_dropped": [0, 0], "fault_dropped": [0, 0],
        "cross_shard_packets": [0, 0], "rx_bytes": [400, 900],
        "tx_bytes": [400, 900], "queue_fill": [0.25, 0.5],
        "heartbeats": [1, 2],
    }
    paths = make_figures(with_metrics, str(tmp_path), "png")
    assert len(paths) == 5
    assert any(p.endswith("shadow_tpu.metrics.png") for p in paths)

"""Config system + simulation assembly tests.

Covers the shadow.config.xml schema both in its modern (<host>/<process>,
stoptime attr) and legacy (<node>/<application>, <kill time>) spellings —
the same dual surface the reference's parser accepts — and runs
config-built simulations end to end (the reference's example config
shapes: a 2-host TGen echo, the 10-peer PHOLD test config).
"""

import textwrap

import jax
import jax.numpy as jnp
import pytest

from shadow_tpu.config import (
    expand_hosts,
    parse_config,
    parse_size,
)
from shadow_tpu.core.timebase import SECOND
from shadow_tpu.models.tgen import parse_tgen_graphml
from shadow_tpu.sim import build_simulation

TOPO_1POI = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="packetloss" attr.type="double" for="edge" id="d4" />
  <key attr.name="latency" attr.type="double" for="edge" id="d3" />
  <key attr.name="bandwidthup" attr.type="int" for="node" id="d2" />
  <key attr.name="bandwidthdown" attr.type="int" for="node" id="d1" />
  <graph edgedefault="undirected">
    <node id="poi-1">
      <data key="d1">10240</data>
      <data key="d2">10240</data>
    </node>
    <edge source="poi-1" target="poi-1">
      <data key="d3">25.0</data>
      <data key="d4">0.0</data>
    </edge>
  </graph>
</graphml>"""


def tgen_config(count=2, sendsize="2KiB", recvsize="10KiB", stoptime=60):
    return textwrap.dedent(f"""\
    <shadow stoptime="{stoptime}">
      <topology><![CDATA[{TOPO_1POI}]]></topology>
      <plugin id="tgen" path="~/.shadow/bin/tgen"/>
      <host id="server" bandwidthup="20480" bandwidthdown="20480">
        <process plugin="tgen" starttime="1" arguments="server port=8888"/>
      </host>
      <host id="client">
        <process plugin="tgen" starttime="2"
          arguments="peers=server:8888 sendsize={sendsize} recvsize={recvsize} count={count} pause=1"/>
      </host>
    </shadow>""")


PHOLD_CONFIG = textwrap.dedent(f"""\
<shadow>
  <topology><![CDATA[{TOPO_1POI}]]></topology>
  <kill time="5"/>
  <plugin id="testphold" path="shadow-plugin-test-phold"/>
  <node id="peer" quantity="10">
    <application plugin="testphold" starttime="1"
      arguments="loglevel=info basename=peer quantity=10 load=5"/>
  </node>
</shadow>""")


# ------------------------------------------------------------------ parsing
def test_parse_modern_config():
    cfg = parse_config(tgen_config())
    assert cfg.stoptime == 60
    assert [p.id for p in cfg.plugins] == ["tgen"]
    assert len(cfg.hosts) == 2
    assert cfg.hosts[0].bandwidthup == 20480
    assert cfg.hosts[1].processes[0].starttime == 2
    assert "poi-1" in cfg.topology_text


def test_parse_legacy_config():
    """<node>/<application>/<kill time> — the reference's own phold test
    config format (src/test/phold/phold.test.shadow.config.xml)."""
    cfg = parse_config(PHOLD_CONFIG)
    assert cfg.stoptime == 5
    assert cfg.hosts[0].quantity == 10
    assert cfg.hosts[0].processes[0].plugin == "testphold"


def test_expand_hosts_quantity_naming():
    cfg = parse_config(PHOLD_CONFIG)
    hosts = expand_hosts(cfg)
    assert len(hosts) == 10
    # counter-prefix naming (docs/3.1: '1.host', '2.host', ...)
    assert hosts[0].name == "1.peer"
    assert hosts[9].name == "10.peer"
    assert [h.gid for h in hosts] == list(range(10))


def test_parse_size():
    assert parse_size("1 MiB") == 2**20
    assert parse_size("512") == 512
    assert parse_size("2kb") == 2000
    assert parse_size("1.5 KiB") == 1536
    with pytest.raises(ValueError):
        parse_size("12 parsecs")


def test_parse_tgen_graphml_reference_example():
    """The exact action-graph shape the reference example ships
    (resource/examples/tgen.client.graphml.xml)."""
    text = """<?xml version="1.0" encoding="utf-8"?>
    <graphml xmlns="http://graphml.graphdrawing.org/xmlns">
      <key attr.name="recvsize" attr.type="string" for="node" id="d5" />
      <key attr.name="sendsize" attr.type="string" for="node" id="d4" />
      <key attr.name="count" attr.type="string" for="node" id="d3" />
      <key attr.name="time" attr.type="string" for="node" id="d2" />
      <key attr.name="peers" attr.type="string" for="node" id="d1" />
      <graph edgedefault="directed">
        <node id="start"><data key="d1">server:8888</data></node>
        <node id="pause"><data key="d2">1,2,3</data></node>
        <node id="end"><data key="d3">100</data></node>
        <node id="stream">
          <data key="d4">1 MiB</data><data key="d5">1 MiB</data>
        </node>
        <edge source="start" target="stream" />
        <edge source="pause" target="start" />
        <edge source="end" target="pause" />
        <edge source="stream" target="end" />
      </graph>
    </graphml>"""
    prof = parse_tgen_graphml(text)
    assert prof.peers == [("server", 8888)]
    assert prof.sendsize == 2**20
    assert prof.recvsize == 2**20
    assert prof.count == 100
    assert prof.pause_s == [1.0, 2.0, 3.0]


# -------------------------------------------------------------- end-to-end
def test_tgen_two_host_echo_end_to_end():
    """BASELINE config #1 shape: 2-host TGen request/response over TCP."""
    cfg = parse_config(tgen_config(count=2, sendsize="2KiB",
                                   recvsize="10KiB"))
    sim = build_simulation(cfg, seed=42)
    st = sim.run()
    app = st.hosts.app
    names = sim.names
    ci = names.index("client")
    si = names.index("server")
    assert int(app.streams_done[ci]) == 2
    # server-side app bytes: 2 streams x 2 KiB requests arrived
    socks = st.hosts.net.sockets
    assert int(socks.rx_bytes[si].sum()) == 2 * 2048
    # client received both 10 KiB replies
    assert int(socks.rx_bytes[ci].sum()) == 2 * 10240
    # completion happened at sane sim times (after start, before stop)
    assert 2 * SECOND < int(app.t_last_done[ci]) < 60 * SECOND


def test_tgen_quantity_clients():
    """Several client instances against one server (quantity expansion)."""
    cfg_text = textwrap.dedent(f"""\
    <shadow stoptime="60">
      <topology><![CDATA[{TOPO_1POI}]]></topology>
      <plugin id="tgen" path="tgen"/>
      <host id="server">
        <process plugin="tgen" starttime="1" arguments="server port=80"/>
      </host>
      <host id="client" quantity="3">
        <process plugin="tgen" starttime="2"
          arguments="peers=server:80 sendsize=1KiB recvsize=4KiB count=1"/>
      </host>
    </shadow>""")
    cfg = parse_config(cfg_text)
    sim = build_simulation(cfg, seed=1)
    assert sim.names == ["server", "1.client", "2.client", "3.client"]
    st = sim.run()
    app = st.hosts.app
    assert [int(x) for x in app.streams_done[1:4]] == [1, 1, 1]
    socks = st.hosts.net.sockets
    assert int(socks.rx_bytes[0].sum()) == 3 * 1024
    for ci in (1, 2, 3):
        assert int(socks.rx_bytes[ci].sum()) == 4096


def test_phold_config_end_to_end():
    """The reference's own phold test config shape: 10 peers, load=5."""
    cfg = parse_config(PHOLD_CONFIG)
    sim = build_simulation(cfg, seed=7)
    st = sim.run()
    app = st.hosts.app
    sent = int(app.n_sent.sum())
    recv = int(app.n_recv.sum())
    # every peer injected its startup load
    assert sent >= 10 * 5
    # messages circulated (receives trigger sends; some still in flight)
    assert recv > 0
    assert sent >= recv
    # closed population: receives can't exceed what was ever sent, and the
    # 25ms-latency loop over 4 sim seconds allows many generations
    assert recv >= 10 * 5  # at least the initial load got delivered

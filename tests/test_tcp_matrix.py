"""The TCP test matrix: {loopback, lossless, lossy} x {reno, cubic, aimd}.

Mirrors the reference's 13-config TCP suite — tcp tests are registered
over {blocking, nonblocking-poll, nonblocking-epoll, nonblocking-select} x
{loopback, lossless, lossy} (reference: src/test/tcp/CMakeLists.txt:14-60;
the lossy variants exercise retransmit/congestion via edge packetloss).
The jitted tier has no blocking-style axis (apps are event handlers), so
the matrix here crosses path class with the congestion-control algorithm
(tcp_cong.h vtable; options.c --tcp-congestion-control) instead; the
blocking-style axis lives in the process tier's shim tests.

Also covers the round-2 fidelity features: delayed ACK halves the pure-ACK
packet stream, receive-window autotuning lifts throughput past the initial
64-segment window, and in-order delivery keeps exact byte totals under
loss.
"""

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from shadow_tpu.core.engine import ConstantNetwork, Engine, EngineConfig
from shadow_tpu.core.events import Events
from shadow_tpu.core.timebase import MILLISECOND, SECOND, TIME_INVALID
from shadow_tpu.host.sockets import PROTO_TCP
from shadow_tpu.transport import tcp as tcpm
from shadow_tpu.transport.stack import HostNet, N_PKT_ARGS, SimHost, Stack
from shadow_tpu.transport.tcp import TCP, emit_concat

KIND_APP = tcpm.N_TCP_KINDS


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class App:
    rx: jax.Array  # i64 server-side app-delivered bytes
    last_rx: jax.Array  # i64


def build(total=100_000, *, loopback=False, reliability=1.0,
          latency=10 * MILLISECOND, bw=1024.0, seed=7, **tcp_kw):
    """Client connects to <server>:80 at t=1ms and streams `total` bytes.

    loopback=True puts both endpoints on one host (the reference's
    loopback configs talk over 127.0.0.1 on a single host); otherwise
    host 0 -> host 1 over the constant-latency path.
    """
    n_hosts = 1 if loopback else 2
    server = 0 if loopback else 1
    cslot = 1 if loopback else 0
    tcp = TCP(**tcp_kw)
    stack = Stack(tcp=tcp)

    def on_recv(hs, slot, pkt, now, key):
        app: App = hs.app
        # the client's slot receives only EOF flags; data lands on the
        # server's child slot (and in loopback both share the host row)
        got = (slot >= 0) & (pkt.length > 0) & (slot != cslot)
        app = dataclasses.replace(
            app,
            rx=app.rx + jnp.where(got, pkt.length.astype(jnp.int64), 0),
            last_rx=jnp.where(got, now, app.last_rx),
        )
        from shadow_tpu.core.engine import Emit

        return dataclasses.replace(hs, app=app), Emit.none(1, N_PKT_ARGS)

    def on_app(hs, ev: Events, key):
        mask = ev.dst == ev.dst  # always; single client host emits
        hs, em1 = tcp.connect(stack, hs, cslot, ev.time, mask=mask)
        hs, em2 = tcp.send(hs, cslot, total, ev.time, mask=mask)
        hs, em3 = tcp.close(hs, cslot, ev.time, mask=mask)
        return hs, emit_concat(em1, em2, em3)

    handlers = stack.make_handlers(on_recv) + [on_app]
    cfg = EngineConfig(
        n_hosts=n_hosts, capacity=512, lookahead=latency,
        max_emit=tcp.min_max_emit(1), n_args=N_PKT_ARGS, seed=seed,
    )
    eng = Engine(cfg, handlers, ConstantNetwork(latency, reliability))

    net = HostNet.create(n_hosts, 8, bw, bw, with_tcp=True)
    tab = net.sockets.bind(server, 0, PROTO_TCP, 80)
    tab = tab.bind(0, cslot, PROTO_TCP, 10_000, peer_host=server,
                   peer_port=80)
    net = dataclasses.replace(net, sockets=tab, tcb=net.tcb.listen(server, 0))
    z = jnp.zeros((n_hosts,), jnp.int64)
    hosts = SimHost(net=net, app=App(rx=z, last_rx=z))

    ev = Events.empty((1,), n_args=N_PKT_ARGS)
    ev = dataclasses.replace(
        ev,
        time=jnp.asarray([1 * MILLISECOND], jnp.int64),
        dst=jnp.zeros((1,), jnp.int32),
        src=jnp.zeros((1,), jnp.int32),
        seq=jnp.zeros((1,), jnp.int32),
        kind=jnp.asarray([KIND_APP], jnp.int32),
    )
    return eng, eng.init_state(hosts, ev)


PATHS = {
    "loopback": dict(loopback=True),
    "lossless": dict(reliability=1.0),
    "lossy": dict(reliability=0.85),
}


@pytest.mark.parametrize("cc", ["reno", "cubic", "aimd"])
@pytest.mark.parametrize("path", list(PATHS))
def test_matrix_transfer_completes(path, cc):
    kw = PATHS[path]
    eng, st = build(total=60_000, cc=cc, seed=3, **kw)
    st = jax.jit(eng.run)(st, jnp.int64(60 * SECOND))
    tcb = st.hosts.net.tcb
    assert int(st.hosts.app.rx.sum()) == 60_000, (path, cc)
    if path == "lossy":
        # loss must be visible to the controller
        assert int(tcb.n_retx.sum()) > 0, (path, cc)
        if cc == "cubic":
            # cubic recorded a loss epoch (W_max captured)
            assert float(tcb.cc_wmax.max()) > 0.0, (path, cc)
    else:
        assert int(tcb.n_retx.sum()) == 0, (path, cc)
    # client connection fully torn down (auto_close on the server side)
    assert int(tcb.state[0, 1 if path == "loopback" else 0]) in (
        tcpm.CLOSED, tcpm.TIME_WAIT,
    )


def test_delack_halves_pure_ack_stream():
    """Delayed ACK: the receiver's wire-packet count (pure ACKs) drops to
    roughly half of the no-delack run (tcp.c delack)."""
    def acks(delack):
        eng, st = build(total=200_000, delack=delack, seed=5)
        st = jax.jit(eng.run)(st, jnp.int64(30 * SECOND))
        # server (host 1) transmits only ACKs in this one-way transfer
        return int(st.hosts.net.nic_tx.pkts[1])

    with_da, without_da = acks(True), acks(False)
    assert with_da < 0.7 * without_da, (with_da, without_da)


def test_autotune_grows_window_past_initial():
    """Receive-window autotuning: on a high-BDP path the advertised
    window must grow past the initial RCV_WND segments and throughput
    must beat the static-64-segment bound (tcp.c:407-598)."""
    total = 6_000_000
    # 8 MiB/s, 50 ms one-way: BDP ~ 820 KiB >> 64 segs (~90 KiB); cubic
    # so cwnd growth isn't the bottleneck once the window opens
    eng, st = build(
        total=total, bw=8192.0, latency=50 * MILLISECOND, seed=9, cc="cubic",
    )
    run = jax.jit(eng.run)
    mid = run(st, jnp.int64(2 * SECOND))
    # the server's child connection advertised more than the initial 64
    # (read mid-transfer: teardown resets the row to the initial window)
    assert int(mid.hosts.net.tcb.rwnd.max()) > tcpm.RCV_WND
    st = run(st, jnp.int64(60 * SECOND))
    assert int(st.hosts.app.rx[1]) == total
    # a static 64-seg window at ~100ms RTT caps at ~0.92 MB/s -> >6.5s;
    # the autotuned run must land well under that bound
    finish_s = int(st.hosts.app.last_rx[1]) / SECOND
    assert finish_s < 5.0, finish_s


@pytest.mark.parametrize("in_order", [False, True])
def test_lossy_exact_totals_both_delivery_modes(in_order):
    eng, st = build(
        total=80_000, reliability=0.8, in_order=in_order, seed=21,
    )
    st = jax.jit(eng.run)(st, jnp.int64(120 * SECOND))
    assert int(st.hosts.app.rx[1]) == 80_000


def test_cubic_beats_or_matches_reno_on_clean_path():
    """Functional sanity: cubic's growth keeps a clean-path bulk transfer
    at least as fast as reno's (same workload, same seed)."""
    def finish(cc):
        eng, st = build(total=500_000, bw=4096.0, cc=cc, seed=2)
        st = jax.jit(eng.run)(st, jnp.int64(30 * SECOND))
        assert int(st.hosts.app.rx[1]) == 500_000
        return int(st.hosts.app.last_rx[1])

    assert finish("cubic") <= finish("reno") * 1.1


def test_sack_limits_retransmissions():
    """SACK scoreboard (tcp.c SACK; tcp_retransmit_tally.cc): under loss
    the sender must never storm-retransmit a whole window — received
    segments are skipped, so total retransmissions stay well below the
    stream's segment count."""
    total = 120_000
    eng, st = build(total=total, reliability=0.85, seed=5)
    st = jax.jit(eng.run)(st, jnp.int64(60 * SECOND))
    assert int(st.hosts.app.rx.sum()) == total
    n_segs = -(-total // tcpm.MSS)
    retx = int(st.hosts.net.tcb.n_retx.sum())
    assert 0 < retx < n_segs, (retx, n_segs)

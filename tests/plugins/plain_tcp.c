/* plain_tcp.c — an ORDINARY POSIX TCP program: no simulator headers, no
 * ShimAPI, just main() + libc. It runs inside shadow-tpu because the
 * build links it against libshadow_interpose ahead of libc
 * (compile_posix_plugin), proving the unmodified-source contract the
 * reference meets with LD_PRELOAD (its equivalent workload:
 * /root/reference/src/test/tcp/test_tcp.c).
 *
 * usage: plain_tcp <blocking|nonblocking-poll|nonblocking-epoll|
 *                   nonblocking-select> <client server_name port nbytes |
 *                   server port>
 *
 * The client sends nbytes of patterned data; the server echoes
 * everything back until EOF; the client verifies the echo and prints
 * "PLAIN_TCP_OK <nbytes> <ms>".
 */
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <poll.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/select.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <time.h>
#include <unistd.h>

typedef enum { WAIT_READ, WAIT_WRITE } waitkind;

static const char* g_mode = "blocking";

static int iowait(int fd, waitkind k) {
    if (!strcmp(g_mode, "nonblocking-poll")) {
        struct pollfd p;
        memset(&p, 0, sizeof p);
        p.fd = fd;
        p.events = (k == WAIT_READ) ? POLLIN : POLLOUT;
        return poll(&p, 1, -1) > 0 ? 0 : -1;
    }
    if (!strcmp(g_mode, "nonblocking-epoll")) {
        int ep = epoll_create(1);
        struct epoll_event ev, out;
        memset(&ev, 0, sizeof ev);
        ev.events = (k == WAIT_READ) ? EPOLLIN : EPOLLOUT;
        ev.data.fd = fd;
        if (epoll_ctl(ep, EPOLL_CTL_ADD, fd, &ev) < 0) return -1;
        int n = epoll_wait(ep, &out, 1, -1);
        close(ep);
        return n > 0 ? 0 : -1;
    }
    if (!strcmp(g_mode, "nonblocking-select")) {
        fd_set set;
        FD_ZERO(&set);
        FD_SET(fd, &set);
        int n = (k == WAIT_READ) ? select(fd + 1, &set, NULL, NULL, NULL)
                                 : select(fd + 1, NULL, &set, NULL, NULL);
        return n > 0 ? 0 : -1;
    }
    return 0; /* blocking mode never waits explicitly */
}

static int nonblocking(void) { return strcmp(g_mode, "blocking") != 0; }

static int run_server(int port) {
    int ls = socket(AF_INET, SOCK_STREAM | (nonblocking() ? SOCK_NONBLOCK : 0), 0);
    if (ls < 0) return 10;
    struct sockaddr_in a;
    memset(&a, 0, sizeof a);
    a.sin_family = AF_INET;
    a.sin_port = htons((unsigned short)port);
    if (bind(ls, (struct sockaddr*)&a, sizeof a) < 0) return 11;
    if (listen(ls, 8) < 0) return 12;

    int cs;
    for (;;) {
        cs = accept(ls, NULL, NULL);
        if (cs >= 0) break;
        if (errno != EAGAIN) return 13;
        if (iowait(ls, WAIT_READ) < 0) return 14;
    }

    char buf[4096];
    long total = 0;
    for (;;) {
        ssize_t n = recv(cs, buf, sizeof buf, 0);
        if (n == 0) break; /* client FIN */
        if (n < 0) {
            if (errno == EAGAIN) {
                if (iowait(cs, WAIT_READ) < 0) return 15;
                continue;
            }
            return 16;
        }
        total += n;
        ssize_t off = 0;
        while (off < n) {
            ssize_t w = send(cs, buf + off, (size_t)(n - off), 0);
            if (w < 0) {
                if (errno == EAGAIN) {
                    if (iowait(cs, WAIT_WRITE) < 0) return 17;
                    continue;
                }
                return 18;
            }
            off += w;
        }
    }
    printf("PLAIN_TCP_SERVER_DONE %ld\n", total);
    close(cs);
    close(ls);
    return 0;
}

static int run_client(const char* server, int port, long nbytes) {
    char service[16];
    snprintf(service, sizeof service, "%d", port);
    struct addrinfo hints, *info = NULL;
    memset(&hints, 0, sizeof hints);
    hints.ai_socktype = SOCK_STREAM;
    if (getaddrinfo(server, service, &hints, &info) != 0) return 20;

    int fd = socket(AF_INET, SOCK_STREAM | (nonblocking() ? SOCK_NONBLOCK : 0), 0);
    if (fd < 0) return 21;
    struct timeval t0, t1;
    gettimeofday(&t0, NULL);
    if (connect(fd, info->ai_addr, info->ai_addrlen) < 0) {
        if (errno != EINPROGRESS) return 22;
        if (iowait(fd, WAIT_WRITE) < 0) return 23;
        int err = 0;
        socklen_t elen = sizeof err;
        if (getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &elen) < 0 || err)
            return 24;
    }
    freeaddrinfo(info);

    char block[1024];
    for (int i = 0; i < (int)sizeof block; i++) block[i] = (char)('a' + i % 26);

    long sent = 0;
    while (sent < nbytes) {
        size_t chunk = sizeof block;
        if ((long)chunk > nbytes - sent) chunk = (size_t)(nbytes - sent);
        ssize_t w = send(fd, block, chunk, 0);
        if (w < 0) {
            if (errno == EAGAIN) {
                if (iowait(fd, WAIT_WRITE) < 0) return 25;
                continue;
            }
            return 26;
        }
        sent += w;
    }
    shutdown(fd, SHUT_WR); /* tell the server we're done sending */

    long got = 0;
    char in[4096];
    while (got < nbytes) {
        ssize_t n = recv(fd, in, sizeof in, 0);
        if (n == 0) break;
        if (n < 0) {
            if (errno == EAGAIN) {
                if (iowait(fd, WAIT_READ) < 0) return 27;
                continue;
            }
            return 28;
        }
        for (ssize_t i = 0; i < n; i++) {
            /* pattern repeats every 1024 bytes, alphabet every 26 */
            char want = (char)('a' + ((got + i) % sizeof block) % 26);
            if (in[i] != want) {
                printf("PLAIN_TCP_CORRUPT at %ld\n", got + i);
                return 29;
            }
        }
        got += n;
    }
    gettimeofday(&t1, NULL);
    if (got != nbytes) {
        printf("PLAIN_TCP_SHORT %ld/%ld\n", got, nbytes);
        return 30;
    }
    long ms = (t1.tv_sec - t0.tv_sec) * 1000 + (t1.tv_usec - t0.tv_usec) / 1000;
    printf("PLAIN_TCP_OK %ld %ld\n", got, ms);
    close(fd);
    return 0;
}

int main(int argc, char** argv) {
    if (argc < 3) {
        fprintf(stderr, "usage: %s mode client|server ...\n", argv[0]);
        return 2;
    }
    g_mode = argv[1];
    if (!strcmp(argv[2], "server")) {
        return run_server(argc > 3 ? atoi(argv[3]) : 8080);
    }
    if (!strcmp(argv[2], "client")) {
        if (argc < 6) return 2;
        return run_client(argv[3], atoi(argv[4]), atol(argv[5]));
    }
    return 2;
}

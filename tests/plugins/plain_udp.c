/* plain_udp.c — UNMODIFIED POSIX datagram pair for the interposer tier.
 *
 * The same dual-role shape as the reference's src/test/udp/test_udp.c
 * (SOCK_DGRAM socket, bind, sendto with an explicit address, recvfrom
 * returning the source address) but exercising a CROSS-HOST path with
 * multiple datagrams and a reply, so both the device UDP routing and
 * the source-address stamping are load-bearing.
 *
 * argv: server <port> <count>
 *       client <server-name> <port> <count>
 * Exit 0 = every datagram arrived intact, in order, with a correct
 * source address on the reply path.
 */
#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

static void fill(char* buf, int n, int tag) {
    for (int i = 0; i < n; i++) buf[i] = (char)((i * 7 + tag) & 0xFF);
}

static int check(const char* buf, int n, int tag) {
    for (int i = 0; i < n; i++)
        if (buf[i] != (char)((i * 7 + tag) & 0xFF)) return 0;
    return 1;
}

static int run_server(int port, int count) {
    int s = socket(AF_INET, SOCK_DGRAM, 0);
    if (s < 0) return 10;
    struct sockaddr_in a = {0};
    a.sin_family = AF_INET;
    a.sin_addr.s_addr = htonl(INADDR_ANY);
    a.sin_port = htons((uint16_t)port);
    if (bind(s, (struct sockaddr*)&a, sizeof a) < 0) return 11;
    char buf[2048];
    for (int i = 0; i < count; i++) {
        struct sockaddr_in from = {0};
        socklen_t flen = sizeof from;
        ssize_t n = recvfrom(s, buf, sizeof buf, 0,
                             (struct sockaddr*)&from, &flen);
        if (n != 1000 + i) return 12;
        if (!check(buf, (int)n, i)) return 13;
        /* echo back to the datagram's source address */
        fill(buf, (int)n, i + 100);
        if (sendto(s, buf, (size_t)n, 0, (struct sockaddr*)&from, flen)
            != n)
            return 14;
    }
    printf("PLAIN_UDP_SERVER_OK %d\n", count);
    return 0;
}

static int run_client(const char* host, int port, int count) {
    int s = socket(AF_INET, SOCK_DGRAM, 0);
    if (s < 0) return 20;
    struct addrinfo hints = {0}, *ai = 0;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_DGRAM;
    char ps[16];
    snprintf(ps, sizeof ps, "%d", port);
    if (getaddrinfo(host, ps, &hints, &ai) != 0 || !ai) return 21;
    char buf[2048];
    for (int i = 0; i < count; i++) {
        int n = 1000 + i;
        fill(buf, n, i);
        if (sendto(s, buf, (size_t)n, 0, ai->ai_addr, ai->ai_addrlen)
            != n)
            return 22;
        struct sockaddr_in from = {0};
        socklen_t flen = sizeof from;
        ssize_t got = recvfrom(s, buf, sizeof buf, 0,
                               (struct sockaddr*)&from, &flen);
        if (got != n) return 23;
        if (!check(buf, (int)got, i + 100)) return 24;
        if (ntohs(from.sin_port) != port) return 25; /* reply source */
    }
    freeaddrinfo(ai);
    printf("PLAIN_UDP_CLIENT_OK %d\n", count);
    return 0;
}

int main(int argc, char** argv) {
    if (argc >= 4 && strcmp(argv[1], "server") == 0)
        return run_server(atoi(argv[2]), atoi(argv[3]));
    if (argc >= 5 && strcmp(argv[1], "client") == 0)
        return run_client(argv[2], atoi(argv[3]), atoi(argv[4]));
    return 2;
}

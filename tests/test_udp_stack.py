"""Transport stack tests: NIC virtual clock, CoDel law, UDP end-to-end.

End-to-end fixture mirrors the reference's UDP test pattern — a client and
server exchanging datagrams inside an embedded 2-host topology
(reference: src/test/udp/test_udp.c + udp.test.shadow.config.xml) — here as
jitted handlers over the device engine.
"""

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from shadow_tpu.core.engine import ConstantNetwork, Emit, Engine, EngineConfig
from shadow_tpu.core.events import Events
from shadow_tpu.core.timebase import MILLISECOND, SECOND
from shadow_tpu.host.nic import CODEL_INTERVAL, CODEL_TARGET, HEADER_UDP, NIC, CoDel
from shadow_tpu.host.sockets import PROTO_UDP, SocketTable
from shadow_tpu.transport.stack import (
    HostNet,
    N_PKT_ARGS,
    N_STACK_KINDS,
    Pkt,
    SimHost,
    Stack,
)


# ----------------------------------------------------------------- NIC unit
def test_nic_virtual_clock():
    nic = NIC.create(jnp.asarray([1024.0]))  # 1024 KiB/s = ~1 MiB/s
    one = jax.tree.map(lambda a: a[0], nic)
    # 1048576 bytes/s -> 1048.576 bytes/ms; 1049 bytes take ~1ms
    n1, start, fin = one.admit(jnp.int64(0), jnp.int32(1049))
    assert int(start) == 0
    assert 950_000 < int(fin) < 1_050_000
    # back-to-back: second packet starts when the first finishes
    n2, start2, fin2 = n1.admit(jnp.int64(0), jnp.int32(1049))
    assert int(start2) == int(fin)
    # idle gap longer than burst allowance: no infinite credit
    n3, start3, fin3 = n2.admit(jnp.int64(10 * SECOND), jnp.int32(1049))
    assert int(start3) == 10 * SECOND
    # unlimited (bootstrap) mode: instant, clock untouched
    n4, s4, f4 = n3.admit(jnp.int64(10 * SECOND), jnp.int32(999999), unlimited=True)
    assert int(s4) == int(f4) == 10 * SECOND
    assert int(n4.free_at) == int(n3.free_at)


def test_codel_control_law():
    cd = jax.tree.map(lambda a: a[0], CoDel.create(1))
    t = jnp.int64(0)
    # below-target sojourns never drop
    for i in range(5):
        cd, drop = cd.on_dequeue(t + i * MILLISECOND, jnp.int64(CODEL_TARGET // 2))
        assert not bool(drop)
    # sustained above-target: first drop only after a full interval elapses
    base = 1 * SECOND
    cd, drop = cd.on_dequeue(jnp.int64(base), jnp.int64(CODEL_TARGET * 2))
    assert not bool(drop)  # arms first_above
    cd, drop = cd.on_dequeue(
        jnp.int64(base + CODEL_INTERVAL // 2), jnp.int64(CODEL_TARGET * 2)
    )
    assert not bool(drop)  # still inside the interval
    cd, drop = cd.on_dequeue(
        jnp.int64(base + CODEL_INTERVAL + 1), jnp.int64(CODEL_TARGET * 2)
    )
    assert bool(drop)  # enters dropping mode
    assert bool(cd.dropping)
    # a below-target packet ends the episode
    cd, drop = cd.on_dequeue(
        jnp.int64(base + CODEL_INTERVAL + 2), jnp.int64(CODEL_TARGET // 2)
    )
    assert not bool(drop)
    assert not bool(cd.dropping)


def test_socket_demux_precedence():
    tab = SocketTable.create(1, 4)
    tab = tab.bind(0, 0, PROTO_UDP, 80)  # wildcard :80
    tab = tab.bind(0, 1, PROTO_UDP, 80, peer_host=7, peer_port=555)  # connected
    row = jax.tree.map(lambda a: a[0], tab)
    # packet from the connected peer goes to the specific socket
    assert int(row.demux(PROTO_UDP, 80, 7, 555)) == 1
    # other peers fall back to the wildcard
    assert int(row.demux(PROTO_UDP, 80, 3, 555)) == 0
    # unbound port: no socket
    assert int(row.demux(PROTO_UDP, 81, 7, 555)) == -1


# ------------------------------------------------------------- end-to-end
KIND_APP_SEND = N_STACK_KINDS  # client self-event: send one datagram


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class EchoApp:
    sent: jax.Array  # i64 per host
    echoed: jax.Array  # server: datagrams echoed back
    acked: jax.Array  # client: echoes received
    last_rx_time: jax.Array  # i64


def build_echo_sim(*, n_datagrams=5, payload=1000, bw_kib=1024.0,
                   latency_ns=10 * MILLISECOND, bootstrap_end=0):
    """Host 0 = client (sends to host 1:80 every 20ms), host 1 = echo server."""
    n_hosts = 2
    stack = Stack(bootstrap_end=bootstrap_end)

    def on_recv(hs, slot, pkt: Pkt, now, key):
        app: EchoApp = hs.app
        is_server = slot == 0  # server binds slot0:80; client uses slot0:10000
        got = slot >= 0
        # server echoes the datagram back to its source
        hs2, em = stack.send_udp(
            hs, now, slot, pkt.src_host, pkt.src_port, pkt.length,
            mask=got & is_server & (pkt.dst_port == 80),
        )
        app = EchoApp(
            sent=app.sent,
            echoed=app.echoed + jnp.where(got & (pkt.dst_port == 80), 1, 0),
            acked=app.acked + jnp.where(got & (pkt.dst_port != 80), 1, 0),
            last_rx_time=jnp.maximum(app.last_rx_time, now),
        )
        return dataclasses.replace(hs2, app=app), em

    def on_app_send(hs, ev: Events, key):
        app: EchoApp = hs.app
        more = app.sent + 1 < n_datagrams
        hs, em_pkt = stack.send_udp(hs, ev.time, 0, jnp.int32(1), 80, payload)
        em_next = Emit.single(
            dst=ev.dst, dt=20 * MILLISECOND, kind=KIND_APP_SEND,
            mask=more, local=True, n_args=N_PKT_ARGS,
        )
        app = dataclasses.replace(app, sent=app.sent + 1)
        em = jax.tree.map(
            lambda a, b: jnp.concatenate([a, b]), em_pkt, em_next
        )
        return dataclasses.replace(hs, app=app), em

    handlers = stack.make_handlers(on_recv) + [on_app_send]
    cfg = EngineConfig(
        n_hosts=n_hosts, capacity=64, lookahead=latency_ns,
        max_emit=2, n_args=N_PKT_ARGS, seed=3,
    )
    eng = Engine(cfg, handlers, ConstantNetwork(latency_ns))

    net = HostNet.create(n_hosts, 4, bw_kib, bw_kib)
    # server: slot0 wildcard :80 ; client: slot0 bound to ephemeral :10000
    tab = net.sockets.bind(1, 0, PROTO_UDP, 80)
    tab = tab.bind(0, 0, PROTO_UDP, 10_000)
    net = dataclasses.replace(net, sockets=tab)
    z = jnp.zeros((n_hosts,), jnp.int64)
    hosts = SimHost(net=net, app=EchoApp(sent=z, echoed=z, acked=z, last_rx_time=z))

    init_ev = Events.empty((1,), n_args=N_PKT_ARGS)
    init_ev = dataclasses.replace(
        init_ev,
        time=jnp.full((1,), MILLISECOND, jnp.int64),
        dst=jnp.zeros((1,), jnp.int32),
        src=jnp.zeros((1,), jnp.int32),
        kind=jnp.full((1,), KIND_APP_SEND, jnp.int32),
    )
    st = eng.init_state(hosts, init_ev)
    return eng, st


def test_udp_echo_end_to_end():
    eng, st = build_echo_sim()
    st = jax.jit(eng.run)(st, jnp.int64(2 * SECOND))
    app = st.hosts.app
    assert int(app.sent[0]) == 5
    assert int(app.echoed[1]) == 5  # server received+echoed all 5
    assert int(app.acked[0]) == 5  # client got all 5 echoes back
    # byte accounting: server rx == 5 datagrams (incl headers on tx counter)
    socks = st.hosts.net.sockets
    assert int(socks.rx_bytes[1, 0]) == 5 * 1000
    assert int(socks.tx_bytes[1, 0]) == 5 * 1000  # payload bytes, both dirs
    # round trip >= 2*latency + 2*serialization
    assert int(app.last_rx_time[0]) > MILLISECOND + 20 * MILLISECOND


def test_udp_echo_bandwidth_slows_delivery():
    # 1000B @ ~1MiB/s ≈ 1ms serialization each way; at 16 KiB/s it's ~64ms
    eng_fast, st_fast = build_echo_sim(n_datagrams=1)
    eng_slow, st_slow = build_echo_sim(n_datagrams=1, bw_kib=16.0)
    st_fast = jax.jit(eng_fast.run)(st_fast, jnp.int64(2 * SECOND))
    st_slow = jax.jit(eng_slow.run)(st_slow, jnp.int64(2 * SECOND))
    rtt_fast = int(st_fast.hosts.app.last_rx_time[0])
    rtt_slow = int(st_slow.hosts.app.last_rx_time[0])
    assert int(st_slow.hosts.app.acked[0]) == 1
    assert rtt_slow > rtt_fast + 100 * MILLISECOND


def test_bootstrap_mode_unlimited():
    # with bootstrap active the 16 KiB/s link behaves like infinite bandwidth
    eng, st = build_echo_sim(n_datagrams=1, bw_kib=16.0, bootstrap_end=5 * SECOND)
    st = jax.jit(eng.run)(st, jnp.int64(2 * SECOND))
    app = st.hosts.app
    assert int(app.acked[0]) == 1
    # pure 2x latency + 2ns rx hops, no serialization
    assert int(app.last_rx_time[0]) <= MILLISECOND + 2 * 10 * MILLISECOND + 10


def test_overload_drops_in_codel():
    """A flood over a thin link must build sojourn and trigger CoDel drops."""
    n_hosts = 2
    stack = Stack()
    payload = 1400

    def on_recv(hs, slot, pkt, now, key):
        app = hs.app
        app = dataclasses.replace(app, echoed=app.echoed + (slot >= 0))
        return dataclasses.replace(hs, app=app), Emit.none(1, N_PKT_ARGS)

    def on_send(hs, ev, key):
        app = hs.app
        more = app.sent + 1 < 400
        hs, em_pkt = stack.send_udp(hs, ev.time, 0, jnp.int32(1), 80, payload)
        em_next = Emit.single(
            dst=ev.dst, dt=MILLISECOND // 2, kind=KIND_APP_SEND,
            mask=more, local=True, n_args=N_PKT_ARGS,
        )
        app = dataclasses.replace(app, sent=app.sent + 1)
        em = jax.tree.map(lambda a, b: jnp.concatenate([a, b]), em_pkt, em_next)
        return dataclasses.replace(hs, app=app), em

    handlers = stack.make_handlers(on_recv) + [on_send]
    cfg = EngineConfig(n_hosts=n_hosts, capacity=1024, lookahead=10 * MILLISECOND,
                       max_emit=2, n_args=N_PKT_ARGS, seed=5)
    eng = Engine(cfg, handlers, ConstantNetwork(10 * MILLISECOND))
    # client uplink fast, server downlink thin (64 KiB/s): ~2800B/ms offered
    net = HostNet.create(n_hosts, 2, 10_000.0, jnp.asarray([10_000.0, 64.0]))
    tab = net.sockets.bind(1, 0, PROTO_UDP, 80).bind(0, 0, PROTO_UDP, 10_000)
    net = dataclasses.replace(net, sockets=tab)
    z = jnp.zeros((n_hosts,), jnp.int64)
    hosts = SimHost(net=net, app=EchoApp(sent=z, echoed=z, acked=z, last_rx_time=z))
    init_ev = Events.empty((1,), n_args=N_PKT_ARGS)
    init_ev = dataclasses.replace(
        init_ev,
        time=jnp.full((1,), MILLISECOND, jnp.int64),
        dst=jnp.zeros((1,), jnp.int32),
        kind=jnp.full((1,), KIND_APP_SEND, jnp.int32),
    )
    st = eng.init_state(hosts, init_ev)
    st = jax.jit(eng.run)(st, jnp.int64(3 * SECOND))
    received = int(st.hosts.app.echoed[1])
    sent = int(st.hosts.app.sent[0])
    assert sent == 400
    assert bool(jnp.any(st.hosts.net.codel.count[1] > 0)), "CoDel never dropped"
    assert received < sent  # drops happened
    assert received > 0

"""Reference test sources, byte-for-byte unmodified, over the simulator.

Each test compiles a file from /root/reference/src/test/ with
compile_posix_plugin and runs it as a virtual process — the same
capstone pattern as test_interpose.py's test_tcp.c run. Covered here:
epoll semantics including EPOLLET/EPOLLONESHOT (epoll.c:34-66) and
signal handling (sigaction + a real SIGSEGV routed to the virtual
process's handler).
"""

import os
import shutil
import textwrap

import pytest

from shadow_tpu.config import parse_config

pytestmark = pytest.mark.skipif(
    shutil.which("gcc") is None, reason="no C toolchain"
)

TOPO = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="packetloss" attr.type="double" for="edge" id="d4" />
  <key attr.name="latency" attr.type="double" for="edge" id="d3" />
  <key attr.name="bandwidthup" attr.type="int" for="node" id="d2" />
  <key attr.name="bandwidthdown" attr.type="int" for="node" id="d1" />
  <graph edgedefault="undirected">
    <node id="poi-1">
      <data key="d1">10240</data>
      <data key="d2">10240</data>
    </node>
    <edge source="poi-1" target="poi-1">
      <data key="d3">25.0</data>
      <data key="d4">0.0</data>
    </edge>
  </graph>
</graphml>"""


def _rerun_in_fresh_process(test_name: str, record_property=None) -> bool:
    """Containment for the sockbuf<->shutdown interaction: when any
    tier already ran in this interpreter, re-execute the named capstone
    in a fresh subprocess (the solo conditions it is known green under)
    and report the child's verdict. Returns True when the child ran.
    The re-exec is surfaced on the pytest report via record_property
    (`reexecuted_in_fresh_process` in the junit/report properties), so
    a green run can be audited for which verdicts came from a child
    interpreter. See the shutdown capstone's docstring for the
    interaction notes."""
    import subprocess
    import sys

    from shadow_tpu.proc import native as _native
    if _native.N_RUNTIMES_CREATED == 0:
        if record_property is not None:
            record_property("reexecuted_in_fresh_process", False)
        return False
    r = subprocess.run(
        [sys.executable, "-m", "pytest", "-q", "-p", "no:cacheprovider",
         f"tests/test_ref_capstones.py::{test_name}"],
        capture_output=True, text=True, timeout=900,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-1000:])
    if record_property is not None:
        record_property("reexecuted_in_fresh_process", True)
    return True


def _run_one(ref_src: str, name: str, seed: int):
    from shadow_tpu.proc import ProcessTier
    from shadow_tpu.proc.native import compile_posix_plugin

    if not os.path.exists(ref_src):
        pytest.skip("reference tree not mounted")
    plug = compile_posix_plugin(
        ref_src, name=name, include_dirs=["/root/reference/src"]
    )
    cfg = parse_config(textwrap.dedent(f"""\
    <shadow stoptime="30">
      <topology><![CDATA[{TOPO}]]></topology>
      <plugin id="{name}" path="{plug}"/>
      <host id="h0">
        <process plugin="{name}" starttime="1" arguments=""/>
      </host>
    </shadow>"""))
    tier = ProcessTier(cfg, seed=seed)
    tier.run()
    return tier


def test_reference_test_epoll_unmodified(capfd):
    """src/test/epoll/test_epoll.c: level/oneshot/edge-trigger pipe
    watches plus the regular-file EPERM check (VERDICT r03 item 9)."""
    tier = _run_one(
        "/root/reference/src/test/epoll/test_epoll.c", "ref_test_epoll", 3
    )
    out = capfd.readouterr().out
    assert tier.exit_codes == {0: 0}, (tier.exit_codes, out[-2000:])
    assert "epoll test passed" in out
    tier.close()


def test_reference_test_signal_unmodified(capfd):
    """src/test/signal/test_signal.c: sigaction installs a SIGSEGV
    handler, the plugin faults on a NULL call, the handler runs and
    exits 0 — a REAL fault routed to the virtual process's handler."""
    tier = _run_one(
        "/root/reference/src/test/signal/test_signal.c", "ref_test_signal",
        4,
    )
    out = capfd.readouterr().out
    assert tier.exit_codes == {0: 0}, (tier.exit_codes, out[-2000:])
    assert "signal test passed" in out
    tier.close()


def test_reference_test_sockbuf_unmodified(capfd, record_property):
    """src/test/sockbuf/test_sockbuf.c (+ its test_common.c helper,
    compiled together): SO_SNDBUF/SO_RCVBUF get/set with the Linux 2x
    rule, user-set sizes disabling autotune, autotuned sizes growing
    across a transfer, SIOCINQ/SIOCOUTQ queue probes, and a
    single-process listener/client/child trio over loopback."""
    from shadow_tpu.proc import ProcessTier
    from shadow_tpu.proc.native import compile_posix_plugin

    src = "/root/reference/src/test/sockbuf/test_sockbuf.c"
    if not os.path.exists(src):
        pytest.skip("reference tree not mounted")
    if _rerun_in_fresh_process("test_reference_test_sockbuf_unmodified",
                               record_property):
        return
    plug = compile_posix_plugin(
        src, name="ref_test_sockbuf",
        extra_sources=["/root/reference/src/test/test_common.c"],
        include_dirs=["/root/reference/src"],
    )
    cfg = parse_config(textwrap.dedent(f"""\
    <shadow stoptime="60">
      <topology><![CDATA[{TOPO}]]></topology>
      <plugin id="ref_test_sockbuf" path="{plug}"/>
      <host id="h0">
        <process plugin="ref_test_sockbuf" starttime="1" arguments=""/>
      </host>
    </shadow>"""))
    tier = ProcessTier(cfg, seed=6)
    tier.run()
    out = capfd.readouterr().out
    assert tier.exit_codes == {0: 0}, (tier.exit_codes, out[-2500:])
    assert "sockbuf test passed" in out
    tier.close()


def test_reference_test_shutdown_unmodified(capfd, record_property):
    """src/test/shutdown/test_shutdown.c (+ test_common.c): real
    shutdown(2) half-close on the TCP machinery — ENOTCONN before
    connect and on UDP, EINVAL on a bad `how`, SHUT_RD reading buffered
    bytes then EOF while sends continue, SHUT_WR sending the FIN after
    queued data drains with later sends failing EPIPE (SIGPIPE ignored
    by the test), all over a single-process loopback trio.

    KNOWN INTERACTION: running this capstone and the sockbuf capstone
    in ONE pytest process hangs whichever runs second — only under
    pytest (the identical back-to-back harness sequence completes in a
    plain python process), implicating pytest's capfd context plus the
    shared green-thread runtime. Containment: when another tier already
    ran in this process, this test re-executes itself in a fresh
    subprocess interpreter, which reproduces the solo conditions it is
    known green under."""
    src = "/root/reference/src/test/shutdown/test_shutdown.c"
    if not os.path.exists(src):
        # skip BEFORE the re-exec branch: a child pytest would report
        # its skip as exit 0 and masquerade as a pass
        pytest.skip("reference tree not mounted")
    if _rerun_in_fresh_process("test_reference_test_shutdown_unmodified",
                               record_property):
        return
    from shadow_tpu.proc import ProcessTier
    from shadow_tpu.proc.native import compile_posix_plugin
    plug = compile_posix_plugin(
        src, name="ref_test_shutdown",
        extra_sources=["/root/reference/src/test/test_common.c"],
        include_dirs=["/root/reference/src"],
    )
    # 1ms loopback: the test usleeps 10ms and expects in-flight bytes to
    # have been delivered by then (it was written for a fast loopback)
    topo_fast = TOPO.replace(
        '<data key="d3">25.0</data>', '<data key="d3">1.0</data>'
    )
    cfg = parse_config(textwrap.dedent(f"""\
    <shadow stoptime="60">
      <topology><![CDATA[{topo_fast}]]></topology>
      <plugin id="ref_test_shutdown" path="{plug}"/>
      <host id="h0">
        <process plugin="ref_test_shutdown" starttime="1" arguments=""/>
      </host>
    </shadow>"""))
    # nine sequential listener/client/child trios; close handshakes
    # recycle slots only once they complete, so give the table headroom
    tier = ProcessTier(cfg, seed=11, n_sockets=48)
    tier.run()
    out = capfd.readouterr().out
    assert tier.exit_codes == {0: 0}, (tier.exit_codes, out[-2500:])
    assert "shutdown test passed" in out
    tier.close()




def test_reference_test_sleep_unmodified(capfd):
    """src/test/sleep/test_sleep.c: sleep/usleep/nanosleep advance the
    virtual clock as observed through BOTH libc clock_gettime and a raw
    syscall(SYS_clock_gettime) — the raw-syscall escape hatch must not
    leak real time."""
    tier = _run_one(
        "/root/reference/src/test/sleep/test_sleep.c", "ref_test_sleep", 7
    )
    out = capfd.readouterr().out
    assert tier.exit_codes == {0: 0}, (tier.exit_codes, out[-2000:])
    assert "sleep test passed" in out
    tier.close()


def test_reference_test_poll_unmodified(capfd):
    """src/test/poll/test_poll.c: poll over simulated pipes (empty,
    filled, timeout) and over a real creat() file fd (always ready —
    poll(2) regular-file semantics)."""
    tier = _run_one(
        "/root/reference/src/test/poll/test_poll.c", "ref_test_poll", 8
    )
    out = capfd.readouterr().out
    assert tier.exit_codes == {0: 0}, (tier.exit_codes, out[-2000:])
    assert "poll test passed" in out
    tier.close()


def test_reference_test_unistd_unmodified(capfd):
    """src/test/unistd/test_unistd.c: virtual getpid (stable, positive)
    and gethostname returning the VIRTUAL host's name (with the
    short-buffer ENAMETOOLONG case). The test detects it runs simulated
    via getenv(SHADOW_SPAWNED) — served by the runtime, the reference's
    re-exec contract (main.c:645-675)."""
    from shadow_tpu.proc import ProcessTier
    from shadow_tpu.proc.native import compile_posix_plugin

    src = "/root/reference/src/test/unistd/test_unistd.c"
    if not os.path.exists(src):
        pytest.skip("reference tree not mounted")
    plug = compile_posix_plugin(
        src, name="ref_test_unistd",
        include_dirs=["/root/reference/src"],
    )
    cfg = parse_config(textwrap.dedent(f"""\
    <shadow stoptime="30">
      <topology><![CDATA[{TOPO}]]></topology>
      <plugin id="ref_test_unistd" path="{plug}"/>
      <host id="vhostname">
        <process plugin="ref_test_unistd" starttime="1"
          arguments="Linux vhostname rel ver x86_64"/>
      </host>
    </shadow>"""))
    tier = ProcessTier(cfg, seed=9)
    tier.run()
    out = capfd.readouterr().out
    assert tier.exit_codes == {0: 0}, (tier.exit_codes, out[-2000:])
    assert "ok: /unistd/gethostname" in out
    tier.close()


def test_reference_test_timerfd_unmodified(capfd):
    """src/test/timerfd/test_timerfd.c: periodic expirations on the
    virtual-time grid (relative and TFD_TIMER_ABSTIME), past-deadline
    timers firing immediately, epoll over timerfds, and disarm. The
    test assumes CLOCK_MONOTONIC's 5-second mark has already passed, so
    the process starts at virtual t=6 (the reference's native runs rely
    on machine uptime for the same assumption)."""
    from shadow_tpu.proc import ProcessTier
    from shadow_tpu.proc.native import compile_posix_plugin

    src = "/root/reference/src/test/timerfd/test_timerfd.c"
    if not os.path.exists(src):
        pytest.skip("reference tree not mounted")
    plug = compile_posix_plugin(src, name="ref_test_timerfd")
    cfg = parse_config(textwrap.dedent(f"""\
    <shadow stoptime="40">
      <topology><![CDATA[{TOPO}]]></topology>
      <plugin id="ref_test_timerfd" path="{plug}"/>
      <host id="h0">
        <process plugin="ref_test_timerfd" starttime="6" arguments=""/>
      </host>
    </shadow>"""))
    tier = ProcessTier(cfg, seed=10)
    tier.run()
    out = capfd.readouterr().out
    assert tier.exit_codes == {0: 0}, (tier.exit_codes, out[-2000:])
    assert "timerfd_epoll test passed" in out
    tier.close()


def test_socketpair_full_duplex(capfd):
    """socketpair(AF_UNIX): both ends read what the other wrote
    (channel.c:22-33 linked byte queues, the reference's Channel)."""
    from shadow_tpu.proc import ProcessTier
    from shadow_tpu.proc.native import compile_posix_plugin

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    src = os.path.join(repo, "native/plugins/_t_sockpair.c")
    with open(src, "w") as f:
        f.write(textwrap.dedent("""\
        #include <stdio.h>
        #include <string.h>
        #include <sys/socket.h>
        #include <unistd.h>

        int main(void) {
            int sv[2];
            if (socketpair(AF_UNIX, SOCK_STREAM, 0, sv) != 0) return 10;
            char buf[16] = {0};
            if (write(sv[0], "ping", 5) != 5) return 11;
            if (read(sv[1], buf, sizeof buf) != 5) return 12;
            if (strcmp(buf, "ping") != 0) return 13;
            if (write(sv[1], "pong", 5) != 5) return 14;  /* reverse */
            memset(buf, 0, sizeof buf);
            if (read(sv[0], buf, sizeof buf) != 5) return 15;
            if (strcmp(buf, "pong") != 0) return 16;
            close(sv[0]);
            if (read(sv[1], buf, sizeof buf) != 0) return 17; /* EOF */
            printf("SOCKETPAIR_OK\\n");
            return 0;
        }
        """))
    plug = compile_posix_plugin(src, name="_t_sockpair")
    cfg = parse_config(textwrap.dedent(f"""\
    <shadow stoptime="30">
      <topology><![CDATA[{TOPO}]]></topology>
      <plugin id="_t_sockpair" path="{plug}"/>
      <host id="h0">
        <process plugin="_t_sockpair" starttime="1" arguments=""/>
      </host>
    </shadow>"""))
    tier = ProcessTier(cfg, seed=5)
    tier.run()
    out = capfd.readouterr().out
    assert tier.exit_codes == {0: 0}, (tier.exit_codes, out[-2000:])
    assert "SOCKETPAIR_OK" in out
    tier.close()
    os.remove(src)


def test_dup_family(capfd):
    """dup/dup2/dup3/F_DUPFD over the simulated stack: duplicates share
    the runtime socket (one write, either fd reads), the object survives
    until the LAST duplicate closes, dup2 redirects onto low fd numbers
    shell-style (process.c descriptor-table dup semantics in the
    reference; preload_defs.h dup rows)."""
    from shadow_tpu.proc import ProcessTier
    from shadow_tpu.proc.native import compile_posix_plugin

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    src = os.path.join(repo, "native/plugins/_t_dup.c")
    with open(src, "w") as f:
        f.write(textwrap.dedent("""\
        #include <fcntl.h>
        #include <stdio.h>
        #include <string.h>
        #include <sys/epoll.h>
        #include <sys/socket.h>
        #include <unistd.h>

        int main(void) {
            int sv[2];
            if (socketpair(AF_UNIX, SOCK_STREAM, 0, sv) != 0) return 10;
            char buf[16] = {0};
            int d = dup(sv[0]);
            if (d < 0) return 11;
            if (write(d, "viaD", 5) != 5) return 12;   /* dup writes */
            if (read(sv[1], buf, sizeof buf) != 5) return 13;
            if (strcmp(buf, "viaD") != 0) return 14;
            close(sv[0]);                     /* original closes... */
            if (write(d, "live", 5) != 5) return 15; /* ...dup lives */
            if (read(sv[1], buf, sizeof buf) != 5) return 16;
            if (strcmp(buf, "live") != 0) return 17;
            if (dup2(d, 5) != 5) return 18;   /* low-fd redirection */
            if (write(5, "lowF", 5) != 5) return 19;
            if (read(sv[1], buf, sizeof buf) != 5) return 20;
            if (strcmp(buf, "lowF") != 0) return 21;
            int t;             /* probe: the host process may hold any
                                  real fd number open (EBUSY there) */
            for (t = 700; t < 900; t++) if (dup2(d, t) == t) break;
            if (t >= 900) return 27;           /* targeted high fd */
            int sv2[2];               /* allocator must skip slot t */
            if (socketpair(AF_UNIX, SOCK_STREAM, 0, sv2) != 0) return 28;
            if (sv2[0] == t || sv2[1] == t) return 29;
            if (write(t, "high", 5) != 5) return 30;
            if (read(sv[1], buf, sizeof buf) != 5) return 31;
            if (strcmp(buf, "high") != 0) return 32;
            /* an epoll watch survives closing the REGISTERED number
               while a duplicate lives (description-keyed on Linux) */
            int ep = epoll_create1(0);
            struct epoll_event e = {EPOLLIN, {.u32 = 77}};
            if (epoll_ctl(ep, EPOLL_CTL_ADD, t, &e) != 0) return 33;
            close(t);                     /* dup d still holds it */
            if (write(sv[1], "ping", 5) != 5) return 34;
            struct epoll_event got;
            if (epoll_wait(ep, &got, 1, 1000) != 1) return 35;
            if (got.data.u32 != 77) return 36;
            if (read(d, buf, sizeof buf) != 5) return 37;
            close(ep);
            close(sv2[0]);
            close(sv2[1]);
            if (dup3(d, d, 0) != -1) return 22;  /* EINVAL, not dup2 */
            if (dup3(d, 5, O_NONBLOCK) != -1) return 38; /* bad flag */
            int f = fcntl(d, F_DUPFD, 0);
            if (f < 0) return 23;
            close(d);
            close(5);
            if (write(f, "last", 5) != 5) return 24; /* last ref live */
            if (read(sv[1], buf, sizeof buf) != 5) return 25;
            close(f);                      /* LAST duplicate: EOF now */
            if (read(sv[1], buf, sizeof buf) != 0) return 26;
            /* daemon-style stdout redirection must shadow the PLUGIN's
               fd 1 without clobbering the simulator's real stdout (the
               harness still captures DUP_OK below) */
            int nul = open("/dev/null", O_WRONLY);
            if (nul < 0) return 40;
            if (dup2(nul, 1) != 1) return 41;
            if (write(1, "swallowed\\n", 10) != 10) return 42;
            close(1);       /* drop the shadow before reporting */
            close(nul);
            printf("DUP_OK\\n");
            return 0;
        }
        """))
    plug = compile_posix_plugin(src, name="_t_dup")
    cfg = parse_config(textwrap.dedent(f"""\
    <shadow stoptime="30">
      <topology><![CDATA[{TOPO}]]></topology>
      <plugin id="_t_dup" path="{plug}"/>
      <host id="h0">
        <process plugin="_t_dup" starttime="1" arguments=""/>
      </host>
    </shadow>"""))
    tier = ProcessTier(cfg, seed=7)
    tier.run()
    out = capfd.readouterr().out
    assert tier.exit_codes == {0: 0}, (tier.exit_codes, out[-2000:])
    assert "DUP_OK" in out
    tier.close()
    os.remove(src)


def test_reference_test_bind_unmodified(capfd):
    """src/test/bind/test_bind.c: bind error-path parity — EINVAL on
    re-bind, EADDRINUSE across sockets (loopback vs ANY included),
    ephemeral bind to port 0, for stream and dgram sockets in blocking
    and nonblocking variants, plus implicit bind at listen observed
    through getsockname."""
    tier = _run_one(
        "/root/reference/src/test/bind/test_bind.c", "ref_test_bind", 12
    )
    out = capfd.readouterr().out
    assert tier.exit_codes == {0: 0}, (tier.exit_codes, out[-2500:])
    assert "ok: /bind/explicit_bind_dgram_nonblock" in out
    assert "ok: /bind/implicit_bind_stream" in out
    tier.close()


def test_reference_test_file_unmodified(capfd, tmp_path, monkeypatch):
    """src/test/file/test_file.c: plugin file IO — fopen/fread/fwrite/
    fprintf/fscanf through real files, fd-level read/write/readv/writev
    (including the EINVAL/EBADF iov edge cases, which pass through to
    kernel semantics), fchmod and fstat."""
    monkeypatch.chdir(tmp_path)  # the test creates files in its cwd
    tier = _run_one(
        "/root/reference/src/test/file/test_file.c", "ref_test_file", 13
    )
    out = capfd.readouterr().out
    assert tier.exit_codes == {0: 0}, (tier.exit_codes, out[-2500:])
    assert "ok: /file/fstat" in out
    tier.close()


def test_reference_test_random_unmodified(capfd):
    """src/test/random/test_random.c: plugin randomness is served by the
    per-(seed, host, pid) deterministic stream — /dev/urandom opens a
    virtual fd whose reads come from the stream (process.c:4321-4324
    semantics) and rand() is interposed (process.c:2676-2677), so the
    test's distribution checks pass without ever touching host entropy."""
    tier = _run_one(
        "/root/reference/src/test/random/test_random.c", "ref_test_random",
        14,
    )
    out = capfd.readouterr().out
    assert tier.exit_codes == {0: 0}, (tier.exit_codes, out[-2000:])
    assert "random test passed" in out
    tier.close()


def test_plugin_randomness_is_deterministic(capfd):
    """Two runs with one seed produce identical urandom/rand() streams;
    a different seed produces a different stream (random.c:15-50
    determinism contract)."""
    from shadow_tpu.proc import ProcessTier
    from shadow_tpu.proc.native import compile_posix_plugin

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    src = os.path.join(repo, "native/plugins/_t_rng.c")
    with open(src, "w") as f:
        f.write(textwrap.dedent("""\
        #include <fcntl.h>
        #include <stdio.h>
        #include <stdlib.h>
        #include <unistd.h>
        int main(void) {
            unsigned v = 0;
            int fd = open("/dev/urandom", O_RDONLY);
            if (fd < 0 || read(fd, &v, sizeof v) != sizeof v) return 1;
            close(fd);
            printf("URND %u RAND %d %d\\n", v, rand(), rand());
            return 0;
        }
        """))
    plug = compile_posix_plugin(src, name="_t_rng")
    cfg_xml = textwrap.dedent(f"""\
    <shadow stoptime="10">
      <topology><![CDATA[{TOPO}]]></topology>
      <plugin id="_t_rng" path="{plug}"/>
      <host id="h0">
        <process plugin="_t_rng" starttime="1" arguments=""/>
      </host>
    </shadow>""")

    def run(seed):
        tier = ProcessTier(parse_config(cfg_xml), seed=seed)
        tier.run()
        out = capfd.readouterr().out
        assert tier.exit_codes == {0: 0}
        tier.close()
        return [l for l in out.splitlines() if l.startswith("URND")][0]

    a, b, c = run(21), run(21), run(22)
    assert a == b, "same seed must reproduce the stream bit-exactly"
    assert a != c, "different seeds must decorrelate the stream"
    os.remove(src)


def test_reference_test_cpp_unmodified(capfd):
    """src/test/cpp/test_cpp.cpp compiled with g++: C++ static
    initializers (global constructors run at plugin load in its
    namespace), iostream/stringstream, and std::chrono::system_clock
    advancing with VIRTUAL time across a sleep(1)."""
    tier = _run_one(
        "/root/reference/src/test/cpp/test_cpp.cpp", "ref_test_cpp", 15
    )
    out = capfd.readouterr().out
    assert tier.exit_codes == {0: 0}, (tier.exit_codes, out[-2000:])
    assert "cpp test passed" in out
    tier.close()
